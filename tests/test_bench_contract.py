"""The driver contract: bench.py must print ONE parseable JSON line with
the agreed schema, and __graft_entry__ must expose entry() and
dryrun_multichip() (the round harness compile-checks and runs these)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _assert_costs_block(costs):
    """The per-leg cost block (ISSUE 5): per-compiled-form FLOPs /
    HBM-bytes / peak-allocation from XLA's cost model, with None
    tolerated field-by-field (backends without cost_analysis report
    None, never zero). Single-program layouts (every bench-contract
    scale) carry the whole-iteration 'step' form with the measured
    per-iteration wall attached; multi-dispatch layouts carry the
    prescale/stripe/final program models unmeasured instead."""
    assert isinstance(costs, dict) and costs
    assert "step" in costs or "final" in costs, costs
    for form, c in costs.items():
        for key in ("flops", "bytes_accessed", "peak_bytes",
                    "bytes_per_edge", "roofline_fraction"):
            assert key in c, (form, key)
            assert c[key] is None or c[key] >= 0, (form, key, c[key])
    if "step" in costs:
        assert costs["step"]["seconds_per_iter"] > 0


def _assert_lowering_block(lowering, expect_native=False):
    """The per-leg compiler-plane block (ISSUE 11; obs/hlo.py):
    per-compiled-form LoweringReport dicts — gather-strategy verdict,
    fusion/while counts, the structural fingerprint the perf-history
    ledger tracks, and the HLO-derived bytes/edge reconciliation.
    None-tolerant as a WHOLE: a backend whose Compiled exposes no
    optimized HLO reports None, never a fabricated block. With
    ``expect_native`` (the CPU test substrate, where HLO text is
    known-available) the whole-iteration program must additionally
    classify NATIVE — the PTH001 invariant riding the bench schema."""
    if lowering is None:
        return
    assert isinstance(lowering, dict) and lowering
    assert "step" in lowering or "final" in lowering, sorted(lowering)
    for form, rep in lowering.items():
        g = rep["gather"]
        assert g["strategy"] in ("native", "expanded", "none"), (form, g)
        assert isinstance(g["expansion_sites"], list)
        assert isinstance(rep["fingerprint"], str) and rep["fingerprint"]
        assert rep["fusion_count"] >= 0 and rep["while_count"] >= 0
        bpe = rep["hlo_bytes_per_edge"]
        assert bpe is None or bpe >= 0, (form, bpe)
        # Raw HLO text never enters JSON artifacts (--dump-hlo is the
        # offline channel).
        assert "text" not in rep, form
    if expect_native:
        whole = lowering.get("step") or lowering.get("final")
        assert whole["gather"]["strategy"] == "native", whole["gather"]


def _assert_graph_block(graph, expect_profile=False, ndev=None):
    """The per-leg data-plane block (ISSUE 13; obs/graph_profile.py):
    structural profile + skew-driven load prediction. None-tolerant as
    a WHOLE (a restored device graph without its artifact reports
    None, never a fabricated block); per-field None-tolerant inside.
    ``expect_profile`` pins the paths that must report (every bench
    rate leg — the builds are fresh, both profile sources exist)."""
    if graph is None:
        assert not expect_profile
        return
    assert isinstance(graph, dict)
    prof = graph.get("profile")
    if expect_profile:
        assert isinstance(prof, dict) and prof, graph
    if prof is not None:
        for key in ("n", "num_edges", "dangling_fraction",
                    "in_hist", "out_hist", "top_hub_ids",
                    "partition_edges", "partition_skew",
                    "powerlaw_alpha", "fingerprint", "source"):
            assert key in prof, key
        assert prof["num_edges"] >= 0
        assert 0.0 <= prof["dangling_fraction"] <= 1.0
        assert len(prof["in_hist"]) == len(prof["out_hist"])
        assert sum(prof["in_hist"]) == prof["n"]
    pred = graph.get("prediction")
    if pred is not None:
        for key in ("ndev", "predicted_straggler_skew",
                    "predicted_halo_head_k"):
            assert key in pred, key
        if ndev is not None:
            assert pred["ndev"] == ndev
        if pred["predicted_straggler_skew"] is not None:
            assert pred["predicted_straggler_skew"] >= 1.0


def _env():
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    return env


def _assert_attribution_block(att, multi_device):
    """The per-leg comms-vs-compute attribution block (ISSUE 10;
    obs/devices.attribute_exchange): fenced exchange-only vs full-step
    wall split plus achieved wire bytes/s against the static comms
    model. Every vertex-sharded leg carries it; multi-device legs must
    carry real numbers (the single-chip leg's model bytes are 0, so
    its achieved rate is legitimately null)."""
    assert isinstance(att, dict), att
    for key in ("iters", "exchange_s", "step_s", "compute_s",
                "exchange_fraction", "model_bytes_per_iter",
                "achieved_bytes_per_sec", "mode"):
        assert key in att, (key, att)
    assert att["exchange_s"] > 0 and att["step_s"] > 0
    assert att["compute_s"] >= 0
    assert 0 <= att["exchange_fraction"] <= 1
    if multi_device:
        assert att["model_bytes_per_iter"] > 0
        assert att["achieved_bytes_per_sec"] > 0
        assert att["mode"] in ("dense", "sparse", "sparse_async")


def _assert_layout_block(layout, form=None):
    """Every rate leg records the RESOLVED kernel/layout/autotune
    decisions (ISSUE 6) so BENCH_r*.json cells are attributable to a
    concrete layout."""
    assert isinstance(layout, dict)
    for key in ("kernel", "pair", "group", "gather_width", "chunk"):
        assert key in layout, (key, layout)
    assert layout["kernel"] in ("ell", "coo") or \
        str(layout["kernel"]).startswith("pallas")
    if form is not None:
        assert layout["form"] == form, layout


def test_bench_json_contract_couple_mode(tmp_path):
    """Default (couple) mode: pair-f64 headline + f32 secondary + the
    partition-centric legs (ISSUE 6) + the standing scale-N accuracy
    field, all in ONE JSON line — which --out writes verbatim as the
    canonical artifact (no {n,cmd,rc,tail,parsed} wrapper) and
    --history appends, normalized, to the perf ledger (ISSUE 9)."""
    out_path = str(tmp_path / "BENCH_fresh.json")
    ledger = str(tmp_path / "ledger.jsonl")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--scale", "10",
         "--iters", "2", "--warmup", "1", "--host-build",
         "--accuracy-scale", "12", "--out", out_path,
         "--history", ledger],
        capture_output=True, text=True, env=_env(), timeout=600,
    )
    assert r.returncode == 0, r.stderr[-800:]
    json_lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(json_lines) == 1, r.stdout
    rec = json.loads(json_lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline",
                        "build_s", "costs", "layout", "lowering",
                        "graph", "sdc_check_overhead_pct", "fast_f32",
                        "partitioned_f32", "pallas_partitioned",
                        "fast_bf16", "accuracy",
                        "env", "scale", "iters", "edge_factor",
                        "schema_version"}
    # SDC overhead (ISSUE 15): None-tolerant when disarmed — the key
    # rides every leg, null without --sdc-check-every.
    assert rec["sdc_check_overhead_pct"] is None
    assert rec["fast_f32"]["sdc_check_overhead_pct"] is None
    # Every bench emit is versioned now (ISSUE 9 satellite); the
    # unversioned r01-r05 artifacts still ingest into the ledger.
    assert rec["schema_version"] >= 2
    assert rec["scale"] == 10 and rec["iters"] == 2
    # --out wrote the SAME canonical record directly (strict JSON).
    with open(out_path) as f:
        assert json.load(f) == rec
    # --history appended one normalized RunRecord with the couple legs.
    with open(ledger) as f:
        lines = [json.loads(l) for l in f.read().splitlines() if l]
    assert len(lines) == 1
    legs = lines[0]["legs"]
    assert {"pair_f64", "fast_f32", "partitioned_f32",
            "pallas_partitioned_f32", "fast_bf16"} <= set(legs)
    assert legs["pair_f64"]["edges_per_sec_per_chip"] == rec["value"]
    assert rec["build_s"] > 0 and rec["fast_f32"]["build_s"] > 0
    # Every leg carries the XLA cost-model block (ISSUE 5) and the
    # resolved-layout record (ISSUE 6).
    _assert_costs_block(rec["costs"])
    _assert_layout_block(rec["layout"])
    # Every leg carries the compiler-plane lowering verdict too
    # (ISSUE 11) — and the CPU substrate exposes HLO, so the verdicts
    # are real (native gather) here, not degraded Nones.
    _assert_lowering_block(rec["lowering"], expect_native=True)
    # Every leg carries the data-plane graph block (ISSUE 13) — and a
    # fresh host build must actually report a profile, not None.
    _assert_graph_block(rec["graph"], expect_profile=True, ndev=1)
    for leg in ("fast_f32", "partitioned_f32", "pallas_partitioned",
                "fast_bf16"):
        _assert_costs_block(rec[leg]["costs"])
        _assert_lowering_block(rec[leg]["lowering"], expect_native=True)
        _assert_graph_block(rec[leg]["graph"], expect_profile=True,
                            ndev=1)
        assert rec[leg]["value"] > 0 and rec[leg]["vs_baseline"] > 0
    # The partitioned legs' profiles record the partition geometry the
    # layout actually ran (per-partition edge counts + skew).
    for leg in ("partitioned_f32", "fast_bf16"):
        prof = rec[leg]["graph"]["profile"]
        assert prof["stripe_span"] == \
            rec[leg]["layout"]["partition_span"]
        assert len(prof["partition_edges"]) >= 2
        assert prof["partition_skew"] >= 1.0
    # The bf16 leg's lowering must PROVE the reduced-precision stream
    # reaches the hot gather (the fast_bf16 mechanical verification).
    bf_whole = (rec["fast_bf16"]["lowering"] or {}).get("step") or {}
    assert (bf_whole.get("gather") or {}).get(
        "hot_gather", {}).get("stream_dtype") == "bf16", bf_whole
    _assert_layout_block(rec["fast_f32"]["layout"], form="step")
    # The partition-centric legs must have ACTUALLY run partitioned,
    # with the geometry recorded (span, window, autotuned chunk).
    for leg in ("partitioned_f32", "fast_bf16"):
        lay = rec[leg]["layout"]
        _assert_layout_block(lay, form="partitioned")
        assert lay["partition_span"] > 0 and lay["window_rows"] > 0
        assert lay["partitions"] >= 1 and lay["chunk"] > 0
    assert rec["fast_bf16"]["layout"]["stream_dtype"] == "bfloat16"
    assert rec["partitioned_f32"]["layout"]["stream_dtype"] is None
    # The fused-kernel leg (ISSUE 16) must have ACTUALLY run the hand
    # kernel (interpret-mode off-TPU) — a probe downgrade would
    # silently re-measure the XLA partitioned leg; kernel_requested in
    # the layout is how a downgrade stays visible, form proves it
    # didn't happen here.
    pl_lay = rec["pallas_partitioned"]["layout"]
    _assert_layout_block(pl_lay, form="pallas_partitioned")
    assert str(pl_lay["kernel"]).startswith("pallas_part")
    assert pl_lay["partition_span"] > 0 and pl_lay["window_rows"] > 0
    assert pl_lay["chunk"] > 0 and pl_lay["group"] == 1
    pl_prof = rec["pallas_partitioned"]["graph"]["profile"]
    assert pl_prof["stripe_span"] == pl_lay["partition_span"]
    assert rec["metric"] == "edges_per_sec_per_chip"
    assert rec["unit"] == "edges/s/chip"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0
    acc = rec["accuracy"]
    assert acc["config"] == "pair-f64"
    assert acc["scale"] == 12 and acc["iters"] == 2
    # The accuracy-grade config must actually be accuracy-grade.
    assert 0 <= acc["normalized_l1_vs_f64_oracle"] < 1e-5
    assert 0 <= acc["mass_normalized_l1"] < 1e-5
    # The fast_bf16 leg ships with its oracle-L1 bound (ISSUE 6
    # acceptance: the pair-f64 oracle chain bounds the bf16 error).
    bf = acc["fast_bf16"]
    assert 0 <= bf["normalized_l1_vs_f64_oracle"] < 5e-2
    assert 0 <= bf["mass_normalized_l1"] < 5e-2


def test_bench_json_contract_single_mode(tmp_path):
    """--dtype selects the original single-config schema."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--scale", "10",
         "--dtype", "float32", "--iters", "2", "--warmup", "1",
         "--host-build", "--no-accuracy"],
        capture_output=True, text=True, env=_env(), timeout=600,
    )
    assert r.returncode == 0, r.stderr[-800:]
    json_lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(json_lines) == 1, r.stdout
    rec = json.loads(json_lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline",
                        "build_s", "costs", "layout", "lowering",
                        "graph", "sdc_check_overhead_pct", "env",
                        "scale", "iters", "edge_factor",
                        "schema_version"}
    assert rec["schema_version"] >= 2
    assert rec["sdc_check_overhead_pct"] is None  # disarmed -> null
    # The environment fingerprint makes future BENCH_r*.json cells
    # comparable across backend drift (ISSUE 4; obs/report.py).
    assert rec["env"]["jax_version"] and rec["env"]["backend"]
    assert rec["value"] > 0 and rec["vs_baseline"] > 0
    _assert_costs_block(rec["costs"])
    _assert_layout_block(rec["layout"])
    _assert_lowering_block(rec["lowering"], expect_native=True)
    _assert_graph_block(rec["graph"], expect_profile=True, ndev=1)


def test_bench_sdc_overhead_leg(tmp_path):
    """--sdc-check-every arms the per-leg SDC detection-overhead
    measurement (ISSUE 15): the single-config record carries a real
    float in ``sdc_check_overhead_pct`` and the --history RunRecord's
    leg folds it into the canonical metric vocabulary."""
    ledger = str(tmp_path / "ledger.jsonl")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--scale", "10",
         "--dtype", "float32", "--iters", "2", "--warmup", "1",
         "--host-build", "--no-accuracy", "--sdc-check-every", "1",
         "--history", ledger],
        capture_output=True, text=True, env=_env(), timeout=600,
    )
    assert r.returncode == 0, r.stderr[-800:]
    json_lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    rec = json.loads(json_lines[0])
    ov = rec["sdc_check_overhead_pct"]
    assert isinstance(ov, float) and ov >= 0.0, rec
    with open(ledger) as f:
        lines = [json.loads(l) for l in f.read().splitlines() if l]
    leg = lines[0]["legs"]["fast_f32"]
    assert leg["sdc_check_overhead_pct"] == ov


def test_bench_build_only_reports_stage_breakdown(tmp_path):
    """--build-only (ISSUE 2): device builds only, ONE JSON line, the
    per-stage breakdown (bench.BUILD_STAGE_KEYS) present for BOTH
    couple legs plus the pair/f32 ratio the 15% gate reads."""
    stage_keys = {"gen_s", "relabel_s", "sort_s", "slots_s", "scatter_s",
                  "autotune_s", "engine_s", "compile_s"}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--scale", "9",
         "--build-only"],
        capture_output=True, text=True, env=_env(), timeout=600,
    )
    assert r.returncode == 0, r.stderr[-800:]
    json_lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(json_lines) == 1, r.stdout
    rec = json.loads(json_lines[0])
    assert set(rec) == {"metric", "value", "unit", "scale", "pair", "f32",
                        "pair_warm", "pair_over_f32", "pair_warm_over_f32",
                        "env", "schema_version"}
    assert rec["schema_version"] >= 2
    assert rec["metric"] == "build_s" and rec["unit"] == "s"
    assert rec["value"] == rec["pair"]["build_s"] > 0
    assert rec["pair_over_f32"] > 0 and rec["pair_warm_over_f32"] > 0
    # The warm pair leg (the 15% gate's comparator) must have paid no
    # stage compiles — everything cached from the cold pair leg.
    assert rec["pair_warm"]["stages"]["compile_s"] == 0.0
    for leg in ("pair", "f32", "pair_warm"):
        stages = rec[leg]["stages"]
        assert set(stages) >= stage_keys, stages
        assert all(stages[k] >= 0 for k in stage_keys)
        assert rec[leg]["num_edges"] > 0


def test_multichip_json_contract(tmp_path):
    """--multichip (ISSUE 8): the promoted MULTICHIP_*.json schema —
    per-leg edges/s/chip, scaling efficiency vs the single-chip leg,
    dense-vs-sparse exchanged-bytes model + accumulated counter, the
    oracle-parity accuracy leg, and the env fingerprint, in ONE JSON
    line over the 8-fake-device CPU mesh."""
    env = _env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    iters = 2
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--multichip",
         "--scale", "10", "--iters", str(iters), "--warmup", "1"],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-800:]
    json_lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(json_lines) == 1, r.stdout
    rec = json.loads(json_lines[0])
    assert set(rec) == {"metric", "value", "unit", "n_devices", "scale",
                        "iters", "single_chip", "dense_exchange",
                        "sparse_exchange", "sparse_async",
                        "pallas_partitioned", "exchange_overlap",
                        "staleness_sweep", "scaling_efficiency",
                        "scaling_efficiency_dense", "exchanged_bytes",
                        "device_view", "accuracy", "env", "edge_factor",
                        "schema_version"}
    assert rec["schema_version"] >= 2
    assert len(rec["device_view"]) == 8
    assert rec["metric"] == "multichip_edges_per_sec_per_chip"
    assert rec["n_devices"] == 8
    for leg in ("single_chip", "dense_exchange", "sparse_exchange",
                "sparse_async"):
        rec_l = rec[leg]
        assert rec_l["value"] > 0 and rec_l["ms_per_iter"] > 0
        _assert_costs_block(rec_l["costs"])
        _assert_layout_block(rec_l["layout"])
        # Multichip legs carry the lowering verdict too (ISSUE 11):
        # the sharded step's collectives land in the collective
        # multiset the fingerprint tracks.
        _assert_lowering_block(rec_l["lowering"], expect_native=True)
        # ... and the data-plane block (ISSUE 13), whose prediction
        # targets the LEG's mesh size.
        _assert_graph_block(rec_l["graph"], expect_profile=True,
                            ndev=rec_l["n_devices"])
        # Comms-vs-compute attribution per leg (ISSUE 10).
        _assert_attribution_block(rec_l["attribution"],
                                  multi_device=leg != "single_chip")
    assert rec["single_chip"]["n_devices"] == 1
    # The fused-kernel multichip leg (ISSUE 16): replicated-rank
    # partitioned pallas form over the same mesh (the hand kernel
    # doesn't compose with the vertex-sharded exchange — _mc_leg
    # docstring), so its comms/attribution blocks are honestly None
    # and its bytes counter honestly zero.
    pl = rec["pallas_partitioned"]
    assert pl["value"] > 0 and pl["n_devices"] == 8
    _assert_costs_block(pl["costs"])
    _assert_layout_block(pl["layout"], form="pallas_partitioned")
    assert str(pl["layout"]["kernel"]).startswith("pallas_part")
    _assert_lowering_block(pl["lowering"], expect_native=True)
    _assert_graph_block(pl["graph"], expect_profile=True, ndev=8)
    assert pl["comms"] is None and pl["attribution"] is None
    assert pl["bytes_exchanged"] == 0
    # The attribution must agree with the leg's own comms model.
    assert rec["sparse_exchange"]["attribution"]["mode"] == "sparse"
    assert rec["sparse_exchange"]["attribution"]["model_bytes_per_iter"] \
        == rec["sparse_exchange"]["comms"]["bytes_per_iter"]
    assert rec["dense_exchange"]["attribution"]["mode"] == "dense"
    assert rec["sparse_exchange"]["layout"]["form"] == "vs_halo"
    assert rec["dense_exchange"]["layout"]["form"] == "vertex_sharded"
    # Headline value IS the sparse leg's rate; efficiency is per-chip
    # rate retained vs the single-chip leg.
    assert rec["value"] == rec["sparse_exchange"]["value"]
    assert rec["scaling_efficiency"] == pytest.approx(
        rec["sparse_exchange"]["value"] / rec["single_chip"]["value"]
    )
    # Comms accounting: the counter accumulates exactly the static
    # model per timed iteration, and the model carries both sides.
    cm = rec["sparse_exchange"]["comms"]
    assert cm["mode"] == "sparse"
    assert cm["sparse_bytes_per_iter"] >= 0
    assert cm["dense_bytes_per_iter"] > 0
    assert rec["sparse_exchange"]["bytes_exchanged"] == \
        iters * cm["bytes_per_iter"]
    assert rec["dense_exchange"]["comms"]["mode"] == "dense"
    # The async stale-boundary leg (ISSUE 17): same wire bytes as the
    # sync sparse exchange (overlap reorders collectives, never adds
    # one), the double-buffer layout recorded, and the leg's own
    # iterations-to-tol from the staleness sweep.
    sa = rec["sparse_async"]
    assert sa["layout"]["form"] == "vs_halo_async"
    assert str(sa["layout"]["halo_async"]).startswith("on:")
    assert sa["comms"]["mode"] == "sparse_async"
    assert sa["comms"]["bytes_per_iter"] == \
        rec["sparse_exchange"]["comms"]["bytes_per_iter"]
    assert sa["bytes_exchanged"] == iters * sa["comms"]["bytes_per_iter"]
    assert sa["comms"]["overlappable_bytes_per_iter"] > 0
    assert sa["attribution"]["mode"] == "sparse_async"
    assert sa["iters_to_tol"] > 0
    # Overlap verdict block: sync compute+exchange sum vs async step
    # wall (the boolean is timing-dependent at toy scale — only the
    # SHAPE is pinned here; the acceptance bench gates the value).
    ov = rec["exchange_overlap"]
    assert set(ov) == {"sync_compute_plus_exchange_s", "async_step_s",
                       "async_below_sync_sum", "gain"}
    assert ov["async_step_s"] > 0
    # Staleness sweep: iterations-to-tol at lag 0 must match the sync
    # schedule (lag-0 reads are fresh by construction).
    sw = rec["staleness_sweep"]
    assert sw["semantics"] == "textbook"
    assert set(sw["legs"]) == {"sync", "async_lag0", "async_lag1"}
    for v in sw["legs"].values():
        assert v["iters_to_tol"] > 0
    assert sw["legs"]["async_lag0"]["iters_to_tol"] == \
        sw["legs"]["sync"]["iters_to_tol"]
    xb = rec["exchanged_bytes"]
    assert set(xb) == {"sparse_model_per_iter", "dense_model_per_iter",
                       "sparse_below_dense", "halo_fraction", "head_k"}
    acc = rec["accuracy"]
    assert acc["scale"] == 10 and acc["iters"] == iters
    assert 0 <= acc["normalized_l1_vs_f64_oracle"] < 1e-3
    assert isinstance(acc["sparse_below_dense"], bool)
    assert rec["env"]["jax_version"] and rec["env"]["backend"] == "cpu"


def test_bench_ppr_serve_contract(tmp_path):
    """--ppr-serve (ISSUE 18/19): ONE JSON line with the serving
    schema, now including the query plane's per-leg p99 decomposition
    (phase_p99_ms), and --history folds those legs into *_p99_ms
    columns on the ppr_serve ledger leg."""
    ledger = str(tmp_path / "ledger.jsonl")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--ppr-serve",
         "--scale", "8", "--iters", "2", "--serve-queries", "24",
         "--serve-qps", "500", "--serve-topk", "8",
         "--history", ledger],
        capture_output=True, text=True, env=_env(), timeout=600,
    )
    assert r.returncode == 0, r.stderr[-800:]
    json_lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(json_lines) == 1, r.stdout
    rec = json.loads(json_lines[0])
    assert set(rec) == {"metric", "value", "unit", "p50_ms", "p99_ms",
                        "phase_p99_ms", "shed_fraction", "rescues",
                        "queries", "answered", "outcomes", "elapsed_s",
                        "offered_qps", "scale", "iters", "edge_factor",
                        "max_batch", "deadline_ms", "queue_depth",
                        "topk", "env", "schema_version"}
    assert rec["metric"] == "ppr_serve_queries_per_sec"
    assert rec["schema_version"] >= 2
    assert rec["queries"] == 24 and rec["answered"] > 0
    # The tail decomposition (ISSUE 19): every leg present, finite,
    # non-negative — the columns the history ledger trends.
    phase = rec["phase_p99_ms"]
    assert set(phase) == {"admission_wait", "batch_wait", "dispatch",
                          "fetch"}
    assert all(isinstance(v, (int, float)) and v >= 0
               for v in phase.values())
    assert phase["dispatch"] > 0     # real dispatches happened
    # --history lifted the decomposition into the ppr_serve leg.
    with open(ledger) as f:
        lines = [json.loads(l) for l in f.read().splitlines() if l]
    assert len(lines) == 1
    leg = lines[0]["legs"]["ppr_serve"]
    for short in ("admission_wait", "batch_wait", "dispatch", "fetch"):
        assert leg[short + "_p99_ms"] == phase[short]
    assert leg["queries_per_sec"] == rec["value"]


def test_graft_entry_contract():
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as ge
    finally:
        sys.path.remove(REPO)
    assert callable(ge.entry) and callable(ge.dryrun_multichip)
    import jax

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)  # compile-check on the test backend (CPU)
    assert out.shape[0] > 0


def test_bench_couple_device_build_reports_warm(tmp_path):
    """Couple mode on the DEVICE-build path (the driver's default)
    reports build_warm_s — the reproducible tuning+compile-cache number
    (VERDICT r4 weak #4); the host path omits it (its cost is numpy
    gen + pack + transfer, which no cache affects)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--scale", "9",
         "--iters", "1", "--warmup", "0", "--no-accuracy"],
        capture_output=True, text=True, env=_env(), timeout=600,
    )
    assert r.returncode == 0, r.stderr[-800:]
    rec = json.loads([l for l in r.stdout.splitlines() if l.startswith("{")][0])
    assert rec["build_warm_s"] > 0
    assert rec["build_s"] > 0
