"""Out-of-core host graph build (ingest/external.py): field-identical
to build_graph under a bounded working-memory cap, across chunkings,
spill-run counts, and input formats (VERDICT r3 missing #2)."""

import numpy as np
import pytest

from pagerank_tpu import build_graph
from pagerank_tpu.ingest import external


def _assert_graphs_equal(a, b):
    assert a.n == b.n
    np.testing.assert_array_equal(a.src, b.src)
    np.testing.assert_array_equal(a.dst, b.dst)
    np.testing.assert_array_equal(a.out_degree, b.out_degree)
    np.testing.assert_array_equal(a.in_degree, b.in_degree)
    np.testing.assert_array_equal(a.dangling_mask, b.dangling_mask)
    np.testing.assert_array_equal(a.zero_in_mask, b.zero_in_mask)
    np.testing.assert_allclose(a.edge_weight, b.edge_weight, rtol=0)


def _random_edges(n, e, seed, dup_frac=0.3):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    # Force duplicates so dedup semantics are exercised.
    ndup = int(e * dup_frac)
    src[:ndup] = src[e - ndup:]
    dst[:ndup] = dst[e - ndup:]
    return src, dst


def test_external_matches_build_graph_many_runs(monkeypatch):
    # A tiny spill-chunk forces MANY sorted runs + a real k-way merge.
    n, e = 500, 20000
    src, dst = _random_edges(n, e, 1)
    ref = build_graph(src, dst, n=n)
    monkeypatch.setattr(external, "_SPILL_BYTES_PER_EDGE", 40 * 300)
    g = external.build_graph_external(
        [(src, dst)], n=n, mem_cap_bytes=64 << 20
    )
    _assert_graphs_equal(g, ref)


def test_external_matches_across_chunkings():
    n, e = 300, 5000
    src, dst = _random_edges(n, e, 2)
    ref = build_graph(src, dst, n=n)
    for k in (1, 3, 7):
        cuts = np.array_split(np.arange(e), k)
        chunks = [(src[c], dst[c]) for c in cuts]
        g = external.build_graph_external(chunks, n=n)
        _assert_graphs_equal(g, ref)


def test_external_n_inference_and_bounds():
    src = np.array([0, 5, 5, 3])
    dst = np.array([1, 2, 2, 9])
    g = external.build_graph_external([(src, dst)])
    assert g.n == 10
    assert g.num_edges == 3  # one duplicate collapsed
    with pytest.raises(ValueError, match="out of range"):
        external.build_graph_external([(src, dst)], n=5)
    with pytest.raises(ValueError, match="empty graph"):
        external.build_graph_external([])


def test_external_text_streaming(tmp_path, monkeypatch):
    n, e = 200, 3000
    src, dst = _random_edges(n, e, 3)
    p = str(tmp_path / "edges.txt")
    with open(p, "w") as f:
        f.write("# comment line\n")
        for s, d in zip(src, dst):
            f.write(f"{s} {d}\n")
    ref = build_graph(src, dst, n=n)
    monkeypatch.setattr(external, "_SPILL_BYTES_PER_EDGE", 40 * 500)
    g = external.build_graph_external(p, n=n, mem_cap_bytes=64 << 20)
    _assert_graphs_equal(g, ref)


def test_external_npz_input(tmp_path):
    from pagerank_tpu.ingest.edgelist import save_binary_edges

    n, e = 150, 2000
    src, dst = _random_edges(n, e, 4)
    p = str(tmp_path / "edges.npz")
    save_binary_edges(p, src, dst, n=n)
    ref = build_graph(src, dst, n=n)
    g = external.build_graph_external(p)
    _assert_graphs_equal(g, ref)


@pytest.mark.parametrize("compressed", [False, True])
def test_npz_streams_chunked(tmp_path, compressed):
    """iter_npz_chunks yields lockstep (src, dst) chunks without ever
    materializing the members (stored AND deflated layouts)."""
    n, e = 300, 10_000
    src, dst = _random_edges(n, e, 6)
    p = str(tmp_path / "edges.npz")
    saver = np.savez_compressed if compressed else np.savez
    saver(p, src=src, dst=dst, n=np.int64(n))
    it, n_hint = external.iter_npz_chunks(p, chunk_edges=1024)
    assert n_hint == n
    got_s, got_d = [], []
    for cs, cd in it:
        assert len(cs) == len(cd) <= 1024
        got_s.append(cs)
        got_d.append(cd)
    assert len(got_s) > 1
    np.testing.assert_array_equal(np.concatenate(got_s), src)
    np.testing.assert_array_equal(np.concatenate(got_d), dst)


def test_npz_stream_bounded_rss(tmp_path):
    """An npz much larger than the chunk streams with traced-allocation
    peak well under the input size (VERDICT r4 #7: the cap holds for
    the binary format, not just text)."""
    import tracemalloc

    e = 2_000_000  # 32 MB of int64 src+dst
    rng = np.random.default_rng(7)
    src = rng.integers(0, 1 << 20, e)
    dst = rng.integers(0, 1 << 20, e)
    p = str(tmp_path / "big.npz")
    np.savez(p, src=src, dst=dst, n=np.int64(1 << 20))
    del src, dst
    it, _ = external.iter_npz_chunks(p, chunk_edges=64 * 1024)
    tracemalloc.start()
    total = 0
    for cs, cd in it:
        total += len(cs)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert total == e
    # Full input is 32 MB; a 64k-edge chunk is 1 MB.
    assert peak < 8 << 20, f"streaming peak {peak} bytes — not bounded"


def test_external_npz_graph_matches_across_chunks(tmp_path, monkeypatch):
    """The streamed-npz external build is field-identical to
    build_graph even when the stream is re-cut into many spill runs.
    200k edges against the 64k-edge chunk floor (cap//bpe pinned at the
    floor by the monkeypatch) forces ~4 npz stream chunks AND ~4 spill
    runs, so the lockstep chunk boundaries feed a real k-way merge."""
    from pagerank_tpu.ingest.edgelist import save_binary_edges

    n, e = 5000, 200_000
    src, dst = _random_edges(n, e, 8)
    p = str(tmp_path / "edges.npz")
    save_binary_edges(p, src, dst, n=n)
    ref = build_graph(src, dst, n=n)
    monkeypatch.setattr(external, "_SPILL_BYTES_PER_EDGE", 1024)
    g = external.build_graph_external(p, mem_cap_bytes=64 << 20)
    _assert_graphs_equal(g, ref)


def test_npz_stream_rejects_mismatched_members(tmp_path):
    p = str(tmp_path / "bad.npz")
    np.savez(p, src=np.arange(5), dst=np.arange(4))
    with pytest.raises(ValueError, match="length mismatch"):
        external.iter_npz_chunks(p, chunk_edges=16)
    p2 = str(tmp_path / "bad2.npz")
    np.savez(p2, src=np.arange(6).reshape(2, 3), dst=np.arange(6))
    with pytest.raises(ValueError, match="1-D"):
        external.iter_npz_chunks(p2, chunk_edges=16)


def test_external_dangling_mask_override():
    src = np.array([0, 1])
    dst = np.array([1, 2])
    mask = np.array([False, False, True, True])  # 2 uncrawled, 3 extra
    ref = build_graph(src, dst, n=4, dangling_mask=mask)
    g = external.build_graph_external([(src, dst)], n=4, dangling_mask=mask)
    _assert_graphs_equal(g, ref)
    with pytest.raises(ValueError, match="out-edges"):
        external.build_graph_external(
            [(src, dst)], n=4,
            dangling_mask=np.array([True, False, False, False]),
        )


def test_external_engine_run_matches():
    """The external build feeds the solver identically."""
    from pagerank_tpu import JaxTpuEngine, PageRankConfig

    n, e = 400, 6000
    src, dst = _random_edges(n, e, 5)
    cfg = PageRankConfig(num_iters=8, dtype="float64", accum_dtype="float64")
    r_ref = JaxTpuEngine(cfg).build(build_graph(src, dst, n=n)).run()
    g = external.build_graph_external([(src, dst)], n=n)
    r_ext = JaxTpuEngine(cfg).build(g).run()
    np.testing.assert_array_equal(r_ext, r_ref)


def _mini_segment(seg, files=5, per_file=40, seed=7):
    """Tiny Common-Crawl-style segment with linkless pages and
    uncrawled targets (the reference's two dangling classes)."""
    import json

    from pagerank_tpu.ingest.seqfile import write_sequence_file

    rng = np.random.default_rng(seed)
    n_crawled = files * per_file

    def url(i):
        return f"http://site{i % 97}.test/p{i}"

    for fi in range(files):
        pairs = []
        for ri in range(per_file):
            u = url(fi * per_file + ri)
            links = []
            if rng.random() >= 0.1:
                for t in rng.integers(0, n_crawled, rng.integers(1, 6)):
                    links.append(
                        f"http://uncrawled{int(t)}.test/"
                        if rng.random() < 0.2 else url(int(t))
                    )
            doc = {"content": {"links": [
                {"type": "a", "href": l} for l in links
            ]}}
            pairs.append((u, json.dumps(doc)))
        write_sequence_file(str(seg / f"metadata-{fi:05d}"), pairs,
                            sync_every=7)


def test_crawl_load_external_matches_in_memory(tmp_path, monkeypatch):
    """Out-of-core crawl build (VERDICT r4 #4): native L1 batches
    drained into the external sort — Graph field-identical to the
    in-memory crawl path, IdMap equal, with a byte-cap small enough to
    force MANY ingest batches and spill runs."""
    from pagerank_tpu.ingest import native
    from pagerank_tpu.ingest.seqfile import expand_seqfile_paths

    if native.get_lib() is None or not hasattr(
        native.get_lib(), "crawl_drain_edges"
    ):
        pytest.skip("native library unavailable")
    seg = tmp_path / "seg"
    seg.mkdir()
    _mini_segment(seg)
    paths = expand_seqfile_paths(str(seg))
    ref = native.crawl_load(paths, "seqfile")
    assert ref is not None
    g_ref, ids_ref = ref

    # Force BOTH small-granularity regimes: the chunk floor drops so
    # this ~500-edge segment spills MANY sorted runs (a real k-way
    # merge — one run would mask merge regressions on the callable-n
    # route), and iter_read_batches degrades to 1-file batches so the
    # drain fires per file.
    monkeypatch.setattr(external, "_MIN_CHUNK_EDGES", 64)
    monkeypatch.setattr(external, "_SPILL_BYTES_PER_EDGE", 1 << 20)
    orig = native.iter_read_batches
    monkeypatch.setattr(
        native, "iter_read_batches",
        lambda paths, window, cap: orig(paths, 1, 1),
    )
    saves = []
    orig_save = external.np.save
    monkeypatch.setattr(
        external.np, "save",
        lambda p, a: (saves.append(p), orig_save(p, a))[1],
    )
    with pytest.raises(ValueError, match="128 MiB"):
        native.crawl_load_external(paths, "seqfile", mem_cap_bytes=64 << 20)
    out = native.crawl_load_external(paths, "seqfile",
                                     mem_cap_bytes=128 << 20)
    assert out is not None
    assert len(saves) > 1, "expected multiple spill runs"
    g, ids = out
    _assert_graphs_equal(g, g_ref)
    assert list(ids.names) == list(ids_ref.names)
    assert g.vertex_names == g_ref.vertex_names


def test_crawl_load_external_cli(tmp_path):
    """--host-mem-cap-gb now composes with SequenceFile inputs through
    the CLI (the r4 loud-reject is gone)."""
    from pagerank_tpu.cli import main
    from pagerank_tpu.ingest import native

    if native.get_lib() is None or not hasattr(
        native.get_lib(), "crawl_drain_edges"
    ):
        pytest.skip("native library unavailable")
    seg = tmp_path / "seg"
    seg.mkdir()
    _mini_segment(seg, files=3, per_file=20)
    out_c = str(tmp_path / "capped.tsv")
    out_u = str(tmp_path / "uncapped.tsv")
    base = ["--iters", "5", "--log-every", "0", "--dtype", "float64"]
    assert main(["--input", str(seg), "--host-mem-cap-gb", "0.125",
                 *base, "--out", out_c]) == 0
    assert main(["--input", str(seg), *base, "--out", out_u]) == 0
    assert open(out_c).read() == open(out_u).read()


def test_crawl_load_external_error_parity(tmp_path):
    """Malformed input mid-stream raises the same exception class as
    the in-memory native path (the shared _iter_ingest_batches
    plumbing), and the temp spill dir is cleaned up."""
    import json as _json
    import os

    from pagerank_tpu.ingest import native
    from pagerank_tpu.ingest.seqfile import (expand_seqfile_paths,
                                             write_sequence_file)

    lib = native.get_lib()
    if lib is None or not hasattr(lib, "crawl_drain_edges"):
        pytest.skip("native library unavailable")
    seg = tmp_path / "seg"
    seg.mkdir()
    ok = [("http://a/", _json.dumps(
        {"content": {"links": [{"type": "a", "href": "http://b/"}]}}))]
    write_sequence_file(str(seg / "metadata-00000"), ok)
    write_sequence_file(str(seg / "metadata-00001"),
                        [("http://c/", "{not json")])
    paths = expand_seqfile_paths(str(seg))
    with pytest.raises(_json.JSONDecodeError):
        native.crawl_load(paths, "seqfile")
    tmp_spill = tmp_path / "spill"
    tmp_spill.mkdir()
    with pytest.raises(_json.JSONDecodeError):
        native.crawl_load_external(paths, "seqfile",
                                   mem_cap_bytes=128 << 20,
                                   tmp_dir=str(tmp_spill))
    assert os.listdir(tmp_spill) == []  # spill runs removed on error
