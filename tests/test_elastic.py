"""Elastic multi-device solve tests (ISSUE 7; docs/ROBUSTNESS.md
"Elastic solve"): the watchdog->rescue handoff in virtual time, a
seed-deterministic device kill that rescues onto the degraded mesh and
still matches the CPU oracle, straggler delays that produce telemetry
but never a rescue, bit-for-bit schedule reproducibility, and
mesh-shape-agnostic snapshots (8-device save -> 1-device resume,
bit-identical at f32 grade)."""

import numpy as np
import pytest

import jax

from pagerank_tpu import JaxTpuEngine, PageRankConfig, build_graph
from pagerank_tpu.engines.cpu import ReferenceCpuEngine
from pagerank_tpu.obs import live as obs_live
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.parallel import mesh as mesh_lib
from pagerank_tpu.parallel.elastic import (
    DeviceHealthMonitor,
    DeviceLostError,
    ElasticExhaustedError,
    ElasticRunner,
    looks_like_device_loss,
)
from pagerank_tpu.testing.faults import (
    DeviceFaultSchedule,
    install_device_faults,
)
from pagerank_tpu.utils.retry import RetryPolicy
from pagerank_tpu.utils.snapshot import Snapshotter, resume_engine

NDEV = len(jax.devices())


def _graph(seed=7, n=512, e=4096):
    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


def _f32_cfg(ndev, iters=12):
    return PageRankConfig(num_iters=iters, dtype="float32",
                          accum_dtype="float32", num_devices=ndev)


def _oracle(graph, iters=12):
    cfg = PageRankConfig(num_iters=iters, dtype="float64",
                         accum_dtype="float64")
    return ReferenceCpuEngine(cfg).build(graph).run()


def _runner(graph, cfg, snap, sched, **kw):
    """ElasticRunner over a fresh engine with the fault shim installed
    (and re-installed on every rebuilt engine), the schedule's own
    liveness probe, and per-iteration snapshots."""
    eng = JaxTpuEngine(cfg).build(graph)
    if snap is not None:
        snap.mesh_meta = eng.snapshot_meta()
    shim_kw = {}
    if "sleep" in kw:
        shim_kw["sleep"] = kw.pop("sleep")
    if "monitor" in kw:
        # The monitor is shared: the shim reports per-device walls to
        # it AND the runner drives its step timing.
        shim_kw["monitor"] = kw["monitor"]
    install_device_faults(eng, sched, **shim_kw)

    def factory(devs):
        return JaxTpuEngine(
            cfg.replace(num_devices=len(devs)), devices=devs
        ).build(graph)

    def rebound(e2):
        install_device_faults(e2, sched)
        if snap is not None:
            snap.mesh_meta = e2.snapshot_meta()

    return ElasticRunner(
        eng, factory, snapshotter=snap,
        liveness=sched.liveness_probe, on_rebuild=rebound, **kw
    )


# -- watchdog -> rescue handoff (virtual time) ------------------------------


def test_watchdog_rescue_handshake_virtual_time():
    t = {"now": 0.0}
    fired = []
    wd = obs_live.StallWatchdog(
        5.0, action="rescue", clock=lambda: t["now"],
        sleep=lambda s: None, interrupt=lambda: fired.append(1),
    )
    wd.heartbeat(0)
    t["now"] = 3.0
    assert wd.check() is False
    assert not wd.rescue_requested
    t["now"] = 9.0
    assert wd.check() is True
    assert fired == [1]
    assert wd.rescue_requested
    # CPU fake devices all answer their liveness echo: classified hang.
    assert "hang" in wd.last_classification
    # One-shot handshake: reading consumes.
    assert wd.consume_rescue() is True
    assert wd.consume_rescue() is False
    # One diagnostic per episode; a heartbeat re-arms.
    assert wd.check() is False
    wd.heartbeat(1)
    t["now"] = 20.0
    assert wd.check() is True


def test_watchdog_rejects_unknown_action():
    with pytest.raises(ValueError):
        obs_live.StallWatchdog(1.0, action="reboot")


def test_watchdog_fire_hands_off_to_runner_rescue():
    """The full handoff: engine.run is interrupted (the watchdog's
    rescue fire), the runner consumes the request, probes liveness,
    finds a casualty, and rebuilds over the survivors."""
    mesh = mesh_lib.make_mesh(min(2, NDEV))
    sentinel = np.arange(4.0)

    class Wedged:
        def __init__(self):
            self.mesh = mesh

        def run(self, **kw):
            raise KeyboardInterrupt  # the watchdog's interrupt_main

    class Good:
        def __init__(self, devs):
            self.mesh = mesh_lib.make_mesh(len(devs), devices=devs)

        def run(self, **kw):
            return sentinel

    wd = obs_live.StallWatchdog(1.0, action="rescue",
                                interrupt=lambda: None)
    wd.rescue_requested = True
    prev = obs_live._WATCHDOG
    obs_live._WATCHDOG = wd  # armed, but no poll thread
    try:
        dead_id = int(mesh.devices.reshape(-1)[0].id)
        runner = ElasticRunner(
            Wedged(), lambda devs: Good(devs), snapshotter=None,
            max_rescues=1,
            liveness=lambda devs, t: {
                int(d.id): int(d.id) != dead_id for d in devs
            },
        )
        out = runner.run()
    finally:
        obs_live._WATCHDOG = prev
    assert out is sentinel
    assert runner.rescues == 1
    assert runner.lost_device_ids == [dead_id]
    assert not wd.rescue_requested  # consumed


def test_watchdog_fire_on_live_mesh_is_not_rescued():
    """A stall with every device answering its probe is a HANG: the
    runner must surface it, never tear down a live mesh."""
    mesh = mesh_lib.make_mesh(min(2, NDEV))

    class Wedged:
        def __init__(self):
            self.mesh = mesh

        def run(self, **kw):
            raise KeyboardInterrupt

    wd = obs_live.StallWatchdog(1.0, action="rescue",
                                interrupt=lambda: None)
    wd.rescue_requested = True
    prev = obs_live._WATCHDOG
    obs_live._WATCHDOG = wd
    try:
        runner = ElasticRunner(
            Wedged(), lambda devs: None, snapshotter=None,
            liveness=lambda devs, t: {int(d.id): True for d in devs},
        )
        with pytest.raises(RuntimeError, match="hang, not device loss"):
            runner.run()
    finally:
        obs_live._WATCHDOG = prev
    assert runner.rescues == 0


def test_plain_keyboard_interrupt_propagates():
    """No watchdog rescue request -> a KeyboardInterrupt is the
    user's ctrl-C, not a stall signal."""
    mesh = mesh_lib.make_mesh(min(2, NDEV))

    class Wedged:
        def __init__(self):
            self.mesh = mesh

        def run(self, **kw):
            raise KeyboardInterrupt

    runner = ElasticRunner(Wedged(), lambda devs: None, snapshotter=None)
    with pytest.raises(KeyboardInterrupt):
        runner.run()


# -- device kill -> rescue -> oracle parity ---------------------------------


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device fake mesh")
def test_device_kill_rescues_on_degraded_mesh_and_matches_oracle(tmp_path):
    g = _graph()
    iters = 12
    cfg = _f32_cfg(min(8, NDEV), iters)
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    sched = DeviceFaultSchedule(seed=5, kill={6: 1})
    runner = _runner(g, cfg, snap, sched, max_rescues=2)
    ndev0 = runner.engine.mesh.devices.size

    ranks = runner.run(
        on_iteration=lambda i, info: snap.save(i + 1,
                                               runner.engine.ranks())
    )
    assert runner.rescues == 1
    assert runner.lost_device_ids == [1]
    assert runner.engine.mesh.devices.size == ndev0 - 1
    assert runner.engine.iteration == iters
    # The post-rescue snapshots record the DEGRADED mesh.
    assert snap.mesh_meta["num_devices"] == ndev0 - 1
    _, meta = snap.load(iters)
    assert meta["mesh"]["num_devices"] == ndev0 - 1
    oracle = _oracle(g, iters)
    l1 = np.abs(ranks - oracle).sum() / np.abs(oracle).sum()
    assert l1 <= 1e-4  # the standing f32-grade gate


@pytest.mark.skipif(NDEV < 3, reason="needs >= 3 fake devices")
def test_rescue_budget_exhausted_raises(tmp_path):
    g = _graph()
    cfg = _f32_cfg(min(8, NDEV), 12)
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    sched = DeviceFaultSchedule(seed=5, kill={3: 0, 7: 1})
    runner = _runner(g, cfg, snap, sched, max_rescues=1)
    with pytest.raises(ElasticExhaustedError) as ei:
        runner.run(on_iteration=lambda i, info: snap.save(
            i + 1, runner.engine.ranks()))
    assert ei.value.rescues == 1
    assert set(ei.value.lost_device_ids) == {0, 1}


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device fake mesh")
def test_rescue_without_snapshot_restarts_from_r0(tmp_path):
    """No valid snapshot to warm-start from: the rescue restarts the
    solve from the initial vector on the degraded mesh (counted in
    elastic.restarts) and still converges to the oracle."""
    g = _graph()
    iters = 10
    cfg = _f32_cfg(min(8, NDEV), iters)
    sched = DeviceFaultSchedule(seed=5, kill={4: 1})
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    runner = _runner(g, cfg, snap, sched, max_rescues=1)
    ranks = runner.run()  # on_iteration never saves -> empty dir
    assert runner.rescues == 1
    assert runner.restarts == 1
    oracle = _oracle(g, iters)
    l1 = np.abs(ranks - oracle).sum() / np.abs(oracle).sum()
    assert l1 <= 1e-4


# -- stragglers: telemetry, never rescue ------------------------------------


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device fake mesh")
def test_straggler_delay_is_telemetry_not_rescue(tmp_path):
    g = _graph()
    iters = 10
    cfg = _f32_cfg(min(8, NDEV), iters)
    obs_metrics.get_registry().reset()

    # Virtual time: injected delays advance the monitor's clock; real
    # steps cost zero virtual seconds.
    vt = {"now": 0.0}
    monitor = DeviceHealthMonitor(straggler_factor=3.0, warmup_steps=1,
                                  clock=lambda: vt["now"])
    sched = DeviceFaultSchedule(seed=3, delay={5: (1, 10.0)})
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    runner = _runner(
        g, cfg, snap, sched, max_rescues=1, monitor=monitor,
        sleep=lambda s: vt.__setitem__("now", vt["now"] + s),
    )
    ranks = runner.run()
    assert runner.rescues == 0  # a slow step is NOT a dead device
    assert runner.engine.mesh.devices.size == min(8, NDEV)
    assert monitor.slow_steps >= 1
    snap_counters = obs_metrics.get_registry().snapshot()["counters"]
    assert snap_counters.get("elastic.slow_steps", 0) >= 1
    assert "elastic.rescues" not in snap_counters
    # The delay changes no math: bit-identical to a fault-free run.
    clean = JaxTpuEngine(cfg).build(g).run()
    np.testing.assert_array_equal(ranks, clean)


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device fake mesh")
def test_poison_routes_to_rollback_not_rescue(tmp_path):
    """A poisoned collective output (NaN state) is the NUMERIC plane's
    problem: health check -> snapshot rollback inside engine.run; the
    rescue path must stay cold."""
    g = _graph()
    iters = 10
    cfg = _f32_cfg(min(8, NDEV), iters)
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    sched = DeviceFaultSchedule(seed=11, poison=[5])
    runner = _runner(g, cfg, snap, sched, max_rescues=1)
    ranks = runner.run(
        on_iteration=lambda i, info: snap.save(i + 1,
                                               runner.engine.ranks())
    )
    assert runner.rescues == 0
    assert runner.engine.health["rollbacks"] >= 1
    oracle = _oracle(g, iters)
    l1 = np.abs(ranks - oracle).sum() / np.abs(oracle).sum()
    assert l1 <= 1e-4


# -- determinism ------------------------------------------------------------


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device fake mesh")
def test_same_seed_schedule_reproduces_bit_for_bit(tmp_path):
    g = _graph()
    cfg = _f32_cfg(min(8, NDEV), 12)

    def chaos(run_id):
        snap = Snapshotter(str(tmp_path / f"run{run_id}"),
                           g.fingerprint(), "reference")
        sched = DeviceFaultSchedule(seed=23, kill={7: 2},
                                    delay={3: (0, 0.0)}, poison=[5])
        runner = _runner(g, cfg, snap, sched, max_rescues=2,
                         sleep=lambda s: None)
        ranks = runner.run(on_iteration=lambda i, info: snap.save(
            i + 1, runner.engine.ranks()))
        return ranks, list(sched.log), runner.rescues

    r1, log1, resc1 = chaos(1)
    r2, log2, resc2 = chaos(2)
    assert log1 == log2
    assert resc1 == resc2 == 1
    np.testing.assert_array_equal(r1, r2)


def test_schedule_rate_faults_are_pure_function_of_seed_iteration():
    devs = list(range(8))
    a = DeviceFaultSchedule(seed=9, kill_rate=0.2, max_faults=3)
    b = DeviceFaultSchedule(seed=9, kill_rate=0.2, max_faults=3)
    for i in range(30):
        assert a.decide(i, devs) == b.decide(i, devs)
    assert a.log == b.log
    assert a.dead == b.dead
    # Re-consulting an iteration (post-rescue recompute) does not
    # re-fire its one-shot faults.
    before = set(a.dead)
    for i in range(30):
        for act in a.decide(i, devs):
            assert act[0] != "kill"
    assert a.dead == before


def test_looks_like_device_loss_is_narrow():
    assert looks_like_device_loss(DeviceLostError("x", [1]))
    assert looks_like_device_loss(RuntimeError("DEVICE_LOST: chip 3"))
    assert not looks_like_device_loss(ValueError("bad shape"))
    assert not looks_like_device_loss(RuntimeError("divide by zero"))


# -- mesh-agnostic snapshots ------------------------------------------------


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device fake mesh")
def test_snapshot_8dev_resumes_on_1dev_bit_identical_f32(tmp_path):
    g = _graph()
    cfg = _f32_cfg(min(8, NDEV), 6)
    eng = JaxTpuEngine(cfg).build(g)
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference",
                       mesh_meta=eng.snapshot_meta())
    eng.run(on_iteration=lambda i, info: snap.save(i + 1, eng.ranks()))
    r_n = eng.ranks()

    e1 = JaxTpuEngine(cfg.replace(num_devices=1)).build(g)
    it = resume_engine(e1, snap)
    assert it == 6
    np.testing.assert_array_equal(e1.ranks(), r_n)  # bit-identical f32
    # Provenance: the snapshot knows which mesh produced it.
    _, meta = snap.load(6)
    assert meta["mesh"]["num_devices"] == min(8, NDEV)
    assert meta["mesh"]["layout"]["form"] is not None


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device fake mesh")
def test_snapshot_1dev_resumes_on_ndev(tmp_path):
    """The other direction: a single-device snapshot re-shards onto a
    multi-device mesh and the counter records the re-shard."""
    g = _graph()
    cfg = _f32_cfg(1, 5)
    eng = JaxTpuEngine(cfg).build(g)
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference",
                       mesh_meta=eng.snapshot_meta())
    eng.run(on_iteration=lambda i, info: snap.save(i + 1, eng.ranks()))
    r1 = eng.ranks()

    obs_metrics.get_registry().reset()
    en = JaxTpuEngine(cfg.replace(num_devices=min(8, NDEV))).build(g)
    assert resume_engine(en, snap) == 5
    np.testing.assert_array_equal(en.ranks(), r1)
    counters = obs_metrics.get_registry().snapshot()["counters"]
    assert counters.get("snapshot.mesh_reshards") == 1


# -- mesh liveness primitives -----------------------------------------------


def test_run_with_deadline_and_liveness_probe():
    assert mesh_lib.run_with_deadline(lambda: 42, 5.0) == 42
    with pytest.raises(mesh_lib.DeadlineExpired):
        import time as _time

        mesh_lib.run_with_deadline(lambda: _time.sleep(5), 0.05)
    with pytest.raises(ZeroDivisionError):
        mesh_lib.run_with_deadline(lambda: 1 // 0, 5.0)
    alive = mesh_lib.probe_liveness(timeout_s=10.0)
    assert set(alive) == {d.id for d in jax.devices()}
    assert all(alive.values())


def test_surviving_devices():
    devs = jax.devices()
    out = mesh_lib.surviving_devices([devs[0].id], devs)
    assert devs[0] not in out and len(out) == len(devs) - 1
    with pytest.raises(RuntimeError):
        mesh_lib.surviving_devices([d.id for d in devs], devs)


# -- distributed-init retry (satellite) -------------------------------------


def test_distributed_init_retries_transient_coordinator_race():
    from pagerank_tpu.parallel.distributed import (
        maybe_initialize_distributed)

    obs_metrics.get_registry().reset()
    calls = {"n": 0}

    def flaky_init(**kw):
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionRefusedError("connection refused")

    policy = RetryPolicy(max_attempts=5, base_delay=0.0,
                         sleep=lambda s: None, seed=0)
    ok = maybe_initialize_distributed(
        coordinator_address="127.0.0.1:9999", num_processes=1,
        process_id=0, retry_policy=policy, _initialize=flaky_init,
    )
    assert ok and calls["n"] == 3
    counters = obs_metrics.get_registry().snapshot()["counters"]
    assert counters.get("distributed.init_retries") == 2


def test_distributed_init_does_not_retry_config_errors():
    from pagerank_tpu.parallel.distributed import (
        maybe_initialize_distributed)

    calls = {"n": 0}

    def bad_config(**kw):
        calls["n"] += 1
        raise ValueError("process_id out of range")

    policy = RetryPolicy(max_attempts=5, base_delay=0.0,
                         sleep=lambda s: None)
    with pytest.raises(ValueError):
        maybe_initialize_distributed(
            coordinator_address="127.0.0.1:9999", num_processes=1,
            process_id=7, retry_policy=policy, _initialize=bad_config,
        )
    assert calls["n"] == 1


# -- config knobs -----------------------------------------------------------


def test_rescue_budget_config():
    from pagerank_tpu.utils.config import RobustnessConfig

    rb = RobustnessConfig().validate()
    assert rb.rescue_budget() == rb.max_rollbacks
    assert RobustnessConfig(max_rescues=7).validate().rescue_budget() == 7
    with pytest.raises(ValueError):
        RobustnessConfig(max_rescues=-1).validate()
    with pytest.raises(ValueError):
        RobustnessConfig(straggler_factor=1.0).validate()


# -- CLI surface ------------------------------------------------------------


def test_cli_rescue_rejects_fused_and_device_build(capsys):
    from pagerank_tpu.cli import main as cli_main

    rc = cli_main(["--synthetic", "uniform:256:1024", "--stall-action",
                   "rescue", "--fused"])
    assert rc == 2
    assert "rescue" in capsys.readouterr().err
    rc = cli_main(["--synthetic", "uniform:256:1024", "--stall-action",
                   "rescue", "--engine", "cpu"])
    assert rc == 2


def test_cli_rescue_path_solves_clean(tmp_path):
    """--stall-action rescue with no faults: the elastic runner drives
    a plain solve to the same result as the default path."""
    from pagerank_tpu.cli import main as cli_main

    out_a = tmp_path / "a.tsv"
    out_b = tmp_path / "b.tsv"
    args = ["--synthetic", "uniform:256:1024", "--iters", "5",
            "--log-every", "0", "--snapshot-dir"]
    rc = cli_main(args + [str(tmp_path / "ck_a"), "--stall-action",
                          "rescue", "--out", str(out_a)])
    assert rc == 0
    rc = cli_main(args + [str(tmp_path / "ck_b"), "--out", str(out_b)])
    assert rc == 0
    assert out_a.read_text() == out_b.read_text()


# -- review regressions -----------------------------------------------------


def test_install_device_faults_is_idempotent():
    """A repeat install (same engine) must REPLACE the shim, not stack
    it — a stacked shim consults the schedule twice per iteration and
    silently breaks bit-for-bit log reproducibility."""
    g = _graph()
    cfg = _f32_cfg(min(2, NDEV), 3)
    eng = JaxTpuEngine(cfg).build(g)
    sched = DeviceFaultSchedule(seed=1)
    install_device_faults(eng, sched)
    install_device_faults(eng, sched)  # idempotent, not double-wrap
    eng.run()
    assert len(sched.log) == 3  # one decision per iteration, not two

    ref = DeviceFaultSchedule(seed=1)
    e2 = JaxTpuEngine(cfg).build(g)
    install_device_faults(e2, ref)
    e2.run()
    assert sched.log == ref.log


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device fake mesh")
def test_rescue_abandons_blocked_warm_start_scan(tmp_path):
    """A warm-start source that cannot answer (the async-writer flush
    blocked on a dead-device decode) must not wedge the rescue: past
    resume_timeout_s the scan is abandoned and the solve restarts
    from r0 on the fresh mesh."""
    import time as _time

    g = _graph()
    iters = 8
    cfg = _f32_cfg(min(8, NDEV), iters)
    inner = Snapshotter(str(tmp_path), g.fingerprint(), "reference")

    class BlockedSnap:
        """Duck-typed rollback/warm-start source whose scan blocks
        far past the rescue's deadline."""

        fingerprint = inner.fingerprint
        semantics = inner.semantics
        mesh_meta = None

        def load_latest_valid(self, **kw):
            _time.sleep(30)
            return inner.load_latest_valid(**kw)

    sched = DeviceFaultSchedule(seed=5, kill={4: 1})
    eng = JaxTpuEngine(cfg).build(g)
    install_device_faults(eng, sched)

    def factory(devs):
        return JaxTpuEngine(
            cfg.replace(num_devices=len(devs)), devices=devs
        ).build(g)

    runner = ElasticRunner(
        eng, factory, snapshotter=BlockedSnap(), max_rescues=1,
        resume_timeout_s=0.2, liveness=sched.liveness_probe,
        on_rebuild=lambda e2: install_device_faults(e2, sched),
    )
    t0 = _time.monotonic()
    ranks = runner.run(on_iteration=lambda i, info: inner.save(
        i + 1, runner.engine.ranks()))
    assert _time.monotonic() - t0 < 20  # never waited out the block
    assert runner.rescues == 1
    assert runner.restarts == 1  # scan abandoned -> r0 restart
    oracle = _oracle(g, iters)
    l1 = np.abs(ranks - oracle).sum() / np.abs(oracle).sum()
    assert l1 <= 1e-4


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device fake mesh")
def test_rescue_rebuilds_halo_tables_for_surviving_mesh(tmp_path):
    """ISSUE-8 satellite: a sparse-exchange (halo) solve that loses a
    device must come back with the halo plan REBUILT for the degraded
    mesh — same-ndev tables would index the wrong blocks. The rescued
    run's plan must equal a fresh build's at the surviving device
    count, and the final ranks must still match the oracle."""
    g = _graph()
    iters = 12
    ndev0 = min(8, NDEV)
    cfg = _f32_cfg(ndev0, iters).replace(
        vertex_sharded=True, halo_exchange=True,
    )
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    sched = DeviceFaultSchedule(seed=5, kill={6: 1})
    runner = _runner(g, cfg, snap, sched, max_rescues=2)
    plan0 = runner.engine._halo_plan
    assert plan0.ndev == ndev0

    ranks = runner.run(
        on_iteration=lambda i, info: snap.save(i + 1,
                                               runner.engine.ranks())
    )
    assert runner.rescues == 1
    assert runner.engine.mesh.devices.size == ndev0 - 1
    plan1 = runner.engine._halo_plan
    assert plan1 is not plan0 and plan1.ndev == ndev0 - 1
    assert plan1.n_vs % (128 * (ndev0 - 1)) == 0
    # The rescued engine's plan is exactly what a fresh build over the
    # same degraded mesh derives — tables included, not just shapes.
    fresh = JaxTpuEngine(
        cfg.replace(num_devices=ndev0 - 1),
        devices=list(runner.engine.mesh.devices.reshape(-1)),
    ).build(g)
    plan2 = fresh._halo_plan
    assert plan1.summary() == plan2.summary()
    for a, b in zip(plan1.send_idx, plan2.send_idx):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(plan1.wsend_start, plan2.wsend_start):
        np.testing.assert_array_equal(a, b)
    oracle = _oracle(g, iters)
    l1 = np.abs(ranks - oracle).sum() / np.abs(oracle).sum()
    assert l1 <= 1e-4  # the standing f32-grade gate


def test_watchdog_classifies_the_solve_mesh_only(monkeypatch):
    """Classification must probe the SOLVE MESH's devices (the
    device_source), not every visible chip — a wedged device the
    solve never uses must not read as OUR device loss."""
    mesh = mesh_lib.make_mesh(min(2, NDEV))
    mesh_devs = list(mesh.devices.reshape(-1))
    seen = {}

    def fake_probe(devices=None, timeout_s=2.0):
        seen["devices"] = devices
        return {int(d.id): True for d in (devices or [])}

    monkeypatch.setattr(mesh_lib, "probe_liveness", fake_probe)
    wd = obs_live.StallWatchdog(
        1.0, action="rescue", interrupt=lambda: None,
        device_source=lambda: mesh_devs,
    )
    assert "hang" in wd._classify()
    assert seen["devices"] == mesh_devs
