"""Graph-construction unit tests (SURVEY.md §4 "Unit": CSR build,
out-degrees, dangling mask, uncrawled-target rows)."""

import numpy as np
import pytest

from pagerank_tpu.graph import build_graph, to_csr_transpose


def test_basic_build():
    # 0->1, 0->2, 1->2, 2->0; 3 exists only as a target of 1->3.
    src = np.array([0, 0, 1, 2, 1])
    dst = np.array([1, 2, 2, 0, 3])
    g = build_graph(src, dst)
    assert g.n == 4
    assert g.num_edges == 5
    np.testing.assert_array_equal(g.out_degree, [2, 2, 1, 0])
    np.testing.assert_array_equal(g.in_degree, [1, 1, 2, 1])
    np.testing.assert_array_equal(g.dangling_mask, [False, False, False, True])
    np.testing.assert_array_equal(g.zero_in_mask, [False, False, False, False])
    # dst-sorted
    assert np.all(np.diff(g.dst) >= 0)


def test_duplicate_edges_collapse_before_out_degree():
    # Quirk §2a.5: .distinct() before groupByKey — out-degree counts
    # unique targets (Sparky.java:124).
    src = np.array([0, 0, 0, 1])
    dst = np.array([1, 1, 1, 0])
    g = build_graph(src, dst)
    assert g.num_edges == 2
    np.testing.assert_array_equal(g.out_degree, [1, 1])
    np.testing.assert_allclose(g.edge_weight, [1.0, 1.0])


def test_self_loops_kept():
    # Quirk §2a.5: self-loops are not filtered.
    g = build_graph(np.array([0, 0]), np.array([0, 1]))
    assert g.num_edges == 2
    assert g.out_degree[0] == 2
    assert not g.dangling_mask[0]


def test_extra_vertices_and_zero_in():
    # A crawled page with no anchor links exists with no edges at all
    # (dangling sentinel, Sparky.java:114-118): vertex 5 here.
    g = build_graph(np.array([0]), np.array([1]), n=6)
    assert g.n == 6
    np.testing.assert_array_equal(
        g.dangling_mask, [False, True, True, True, True, True]
    )
    np.testing.assert_array_equal(
        g.zero_in_mask, [True, False, True, True, True, True]
    )


def test_edge_weight_is_inv_unique_outdegree():
    src = np.array([0, 0, 0])
    dst = np.array([1, 2, 3])
    g = build_graph(src, dst)
    np.testing.assert_allclose(g.edge_weight, 1.0 / 3.0)


def test_csr_transpose_matches_manual_spmv():
    rng = np.random.default_rng(0)
    n, e = 50, 400
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    at = to_csr_transpose(g)
    r = rng.random(n)
    expected = np.zeros(n)
    for s, d, w in zip(g.src, g.dst, g.edge_weight):
        expected[d] += w * r[s]
    np.testing.assert_allclose(at @ r, expected, rtol=1e-12)


def test_out_of_range_edge_raises():
    with pytest.raises(ValueError):
        build_graph(np.array([0]), np.array([5]), n=3)


def test_empty_graph_raises():
    with pytest.raises(ValueError):
        build_graph(np.array([], dtype=np.int64), np.array([], dtype=np.int64))


def test_fingerprint_stable_and_structure_sensitive():
    g1 = build_graph(np.array([0, 1]), np.array([1, 0]))
    g2 = build_graph(np.array([0, 1]), np.array([1, 0]))
    g3 = build_graph(np.array([0, 1]), np.array([1, 1]))
    assert g1.fingerprint() == g2.fingerprint()
    assert g1.fingerprint() != g3.fingerprint()
