"""Multi-host (DCN) validation: the sharded engine under a REAL
two-process ``jax.distributed`` runtime (SURVEY.md §5 "Distributed
communication backend" — the reference's Spark cluster manager + netty
shuffle, rebuilt as jax.distributed + XLA collectives).

Two worker processes × 2 fake CPU devices each form a 4-device global
mesh; the final ranks must match a single-process 4-device run of the
same graph bit-for-bit (the deterministic-reduction guarantee of
SURVEY.md §4 "Distributed without a cluster", extended across process
boundaries).
"""

import os
import socket
import subprocess
import sys

import numpy as np


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_matches_single_process(tmp_path):
    # Bounded by the workers' communicate(timeout=240) below.
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    out = str(tmp_path / "ranks.npy")
    coordinator = f"127.0.0.1:{_free_port()}"

    env = {
        k: v
        for k, v in os.environ.items()
        # Workers set their own platform/device-count flags; drop the
        # conftest's so they don't double-apply.
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, str(pid), out],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240) for p in procs]
    finally:
        for p in procs:  # never leak hung workers (coordinator port!)
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, (so, se) in zip(procs, outs):
        if p.returncode != 0 and (
            "Multiprocess computations aren't implemented" in se
        ):
            # jax 0.4.x's CPU backend has no multiprocess collective
            # support; the DCN path needs a newer jax (or real TPUs).
            import pytest

            pytest.skip("CPU backend lacks multiprocess collectives "
                        "on this jax version")
        assert p.returncode == 0, f"worker failed:\n{se[-2000:]}"

    multi = np.load(out)

    # Single-process oracle on an equivalent 4-device mesh (the test
    # session itself runs with 8 fake devices; cap at 4).
    from pagerank_tpu import JaxTpuEngine, PageRankConfig, build_graph

    rng = np.random.default_rng(0)
    n, e = 400, 4000
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    cfg = PageRankConfig(
        num_iters=10, dtype="float64", accum_dtype="float64", lane_group=8,
        num_devices=4,
    )
    single = JaxTpuEngine(cfg).build(g).run_fast()
    np.testing.assert_array_equal(multi, single)
