"""Differential tests: the native C++ L1 (native/crawl_ingest.cpp via
ingest/native.py:crawl_load) against the pure-Python ingest path — the
Python reader (ingest/seqfile.py + ingest/crawljson.py) is the
behavioral spec, quirks included, so the native path must produce the
EXACT same graph: same ids (insertion order), same names, same edges,
same dangling/crawled masks, same strict-mode exception classes.
"""

import json
import math
import os
import struct

import numpy as np
import pytest

from pagerank_tpu.ingest import native
from pagerank_tpu.ingest.crawljson import load_crawl_file
from pagerank_tpu.ingest.seqfile import load_crawl_seqfile, write_sequence_file

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


def assert_same(result_py, result_nat):
    g1, im1 = result_py
    g2, im2 = result_nat
    assert im1.names == im2.names
    assert g1.n == g2.n
    np.testing.assert_array_equal(g1.src, g2.src)
    np.testing.assert_array_equal(g1.dst, g2.dst)
    np.testing.assert_array_equal(g1.out_degree, g2.out_degree)
    np.testing.assert_array_equal(g1.in_degree, g2.in_degree)
    np.testing.assert_array_equal(g1.dangling_mask, g2.dangling_mask)
    np.testing.assert_array_equal(g1.zero_in_mask, g2.zero_in_mask)
    # IdMap lookups agree
    for name in im1.names[: min(50, len(im1.names))]:
        assert im1.get(name) == im2.get(name)


def both_seqfile(tmp_path, records, compression="none", strict=True):
    p = str(tmp_path / f"seg-{compression}")
    write_sequence_file(p, records, compression=compression, sync_every=3)
    py = load_crawl_seqfile(p, strict=strict, native="off")
    nat = load_crawl_seqfile(p, strict=strict, native="auto")
    return py, nat


def both_tsv(tmp_path, lines, strict=True):
    p = str(tmp_path / "crawl.tsv")
    with open(p, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    py = load_crawl_file(p, strict=strict, native="off")
    nat = load_crawl_file(p, strict=strict, native="auto")
    return py, nat


def meta(targets, types=None):
    links = [
        {"type": ("a" if types is None else types[i]), "href": t}
        for i, t in enumerate(targets)
    ]
    return json.dumps({"content": {"links": links}}, ensure_ascii=False)


# ---------------------------------------------------------------------------
# String/value rendering quirks (Gson toString semantics — crawljson.py)
# ---------------------------------------------------------------------------


ADVERSARIAL_HREFS = [
    "http://plain/",
    'quo"ted',                      # embedded quote vanishes (strip-all)
    'back\\slash',                  # dumps doubles it, strip keeps both
    "tab\there",                    # control chars re-escaped by dumps
    "new\nline",
    "bell\x07gamma\x01",            #  /  escapes
    "unicode: é中\U0001F600",  # non-ASCII passes through
    "mixed\"\\\"x",
    "",                             # empty href
    "sp ace",
    "\x1f\x7f",                     # 0x1f escaped, DEL not
]


def test_string_quirks_parity(tmp_path):
    records = [("http://src/", meta(ADVERSARIAL_HREFS))]
    py, nat = both_seqfile(tmp_path, records)
    assert_same(py, nat)
    # and the names really went through the quote-strip + dumps pipeline:
    # dumps escapes the quote to \" and strip-all-quotes leaves the
    # backslash (Sparky.java:105 on the Gson rendering)
    assert 'quo\\ted' in py[1].names
    assert 'back\\\\slash' in py[1].names


def test_nonstring_href_rendering_parity(tmp_path):
    """Non-string hrefs render via json.dumps (ints, floats, bools,
    null, nested containers with ', '/': ' separators)."""
    payload = {
        "content": {"links": [
            {"type": "a", "href": 42},
            {"type": "a", "href": -0},
            {"type": "a", "href": 123456789012345678901234567890},
            {"type": "a", "href": True},
            {"type": "a", "href": False},
            {"type": "a", "href": None},
            {"type": "a", "href": [1, "two", {"three": 3.5}]},
            {"type": "a", "href": {"k": [None, -7], "j": "s"}},
        ]}
    }
    records = [("http://src/", json.dumps(payload))]
    py, nat = both_seqfile(tmp_path, records)
    assert_same(py, nat)


def test_float_repr_parity(tmp_path):
    """Python float repr (shortest round-trip, fixed/scientific switch
    at 1e16 and 1e-4, 2-digit exponent padding) must match to the byte."""
    floats = [
        0.0, -0.0, 1.0, 100.0, 1e15, 1e16, 9999999999999998.0,
        1e-4, 1e-5, 1.5e-5, 123.456, 0.1, 2.675, 1e300, -1e300,
        5e-324, 1.7976931348623157e308, 3.141592653589793,
        1e22, 1e23, -7.066e-9,
    ]
    rng = np.random.default_rng(7)
    floats += [
        float(x)
        for x in rng.standard_normal(60)
        * 10.0 ** rng.integers(-30, 30, 60).astype(float)
    ]
    # tokens via repr -> valid JSON numbers
    links = ", ".join(
        '{"type": "a", "href": %s}' % repr(f) for f in floats
        if math.isfinite(f)
    )
    doc = '{"content": {"links": [%s]}}' % links
    py, nat = both_tsv(tmp_path, ["http://src/\t" + doc])
    assert_same(py, nat)


def test_escape_and_surrogate_parity(tmp_path):
    r"""\uXXXX escapes: pairs combine, lone surrogates survive, and the
    escaped form re-renders through dumps identically."""
    doc = (
        '{"content": {"links": ['
        '{"type": "a", "href": "esc\\u0041\\u00e9\\ud83d\\ude00"},'
        '{"type": "a", "href": "lone\\ud800tail"},'
        '{"type": "a", "href": "low\\udc3ax"},'
        '{"type": "a", "href": "\\/slash\\b\\f\\n\\r\\t"}'
        ']}}'
    )
    py, nat = both_tsv(tmp_path, ["http://src/\t" + doc])
    assert_same(py, nat)


def test_duplicate_keys_last_wins(tmp_path):
    doc = (
        '{"content": {"links": ['
        '{"type": "x", "href": "skipme", "type": "a", "href": "kept"}'
        ']},'
        ' "content": {"links": [{"type": "a", "href": "outer-dup"}]}}'
    )
    py, nat = both_tsv(tmp_path, ["http://src/\t" + doc])
    assert_same(py, nat)
    assert "outer-dup" in py[1].names  # last content wins
    assert "kept" not in py[1].names


def test_structure_tolerance_parity(tmp_path):
    """content/links absent, null, or of the wrong type -> crawled
    record with no targets (isinstance checks in crawljson.py)."""
    docs = [
        "{}", "null", "[]", '"str"', "7", "true",
        '{"content": null}', '{"content": 5}', '{"content": []}',
        '{"content": {"links": null}}', '{"content": {"links": {}}}',
        '{"content": {"links": "zz"}}',
        '{"content": {"links": []}}',
        # type variants that must NOT match "a"
        '{"content": {"links": [{"type": "A", "href": "x"}]}}',
        '{"content": {"links": [{"type": "ab", "href": "x"}]}}',
        '{"content": {"links": [{"type": 1, "href": "x"}]}}',
        '{"content": {"links": [{"type": null, "href": "x"}]}}',
        '{"content": {"links": [{"type": true, "href": "x"}]}}',
    ]
    records = [(f"http://u{i}/", d) for i, d in enumerate(docs)]
    py, nat = both_seqfile(tmp_path, records)
    assert_same(py, nat)


def test_json_oddities_accepted(tmp_path):
    """Python json accepts NaN/Infinity constants and deep whitespace."""
    docs = [
        '{"content": {"links": [{"type": "a", "href": NaN}]}}',
        '{"content": {"links": [{"type": "a", "href": Infinity}]}}',
        '{"content": {"links": [{"type": "a", "href": -Infinity}]}}',
        ' \t\n\r{ "content" : { "links" : [ ] } } \n',
    ]
    records = [(f"http://u{i}/", d) for i, d in enumerate(docs)]
    py, nat = both_seqfile(tmp_path, records)
    assert_same(py, nat)


# ---------------------------------------------------------------------------
# Strict / non-strict error semantics
# ---------------------------------------------------------------------------


BAD_RECORDS = [
    # (doc, exception type in strict mode)
    ('{"content": {"links": [{"href": "x"}]}}', KeyError),       # no type
    ('{"content": {"links": [{"type": "a"}]}}', KeyError),       # no href
    ('{"content": {"links": ["notdict"]}}', TypeError),
    ('{"content": {"links": [5]}}', TypeError),
    ('{"content": {"links": [[1]]}}', TypeError),
    ('{broken', json.JSONDecodeError),
    ('{"content": {"links": [{"type": "a", "href": "x"}]}', json.JSONDecodeError),
    ('{"a": 01}', json.JSONDecodeError),                          # leading zero
    ('{"a": "un\x01escaped"}', json.JSONDecodeError),             # raw control
    ("", json.JSONDecodeError),
]


@pytest.mark.parametrize("doc,exc", BAD_RECORDS)
def test_strict_error_class_parity(tmp_path, doc, exc):
    p = str(tmp_path / "seg")
    write_sequence_file(p, [("http://ok/", meta(["http://t/"])),
                            ("http://bad/", doc)])
    with pytest.raises(exc):
        load_crawl_seqfile(p, strict=True, native="off")
    with pytest.raises(exc):
        load_crawl_seqfile(p, strict=True, native="auto")


def test_nonstrict_skips_parity(tmp_path):
    """Non-strict mode keeps the record (crawled, no targets on JSON
    errors; per-entry skip on bad entries) — both paths identically."""
    records = [("http://ok/", meta(["http://t/"]))]
    records += [(f"http://bad{i}/", doc) for i, (doc, _) in enumerate(BAD_RECORDS)]
    records += [("http://mixed/",
                 '{"content": {"links": [{"type": "a", "href": "good1"}, '
                 '{"href": "nope"}, "str", {"type": "a", "href": "good2"}]}}')]
    py, nat = both_seqfile(tmp_path, records, strict=False)
    assert_same(py, nat)
    assert "good1" in py[1].names and "good2" in py[1].names


def test_jsonl_parity_and_errors(tmp_path):
    lines = [
        json.dumps({"url": "http://a/", "metadata":
                    {"content": {"links": [{"type": "a", "href": "http://b/"}]}}}),
        json.dumps({"url": "http://c/", "json":
                    {"content": {"links": [{"type": "a", "href": "http://a/"}]}}}),
        json.dumps({"url": "http://d/"}),          # no metadata -> {} root
        json.dumps({"url": "http://e/", "metadata": None}),
        "http://tsv/\t" + meta(["http://a/"]),     # mixed TSV line
    ]
    py, nat = both_tsv(tmp_path, lines)
    assert_same(py, nat)
    # JSONL structural errors raise in BOTH modes (outside the strict
    # try in iter_crawl_records)
    for bad, exc in [("{notjson", json.JSONDecodeError),
                     ('{"nourl": 1}', KeyError),
                     ("[1, 2]", TypeError)]:
        for strict in (True, False):
            with pytest.raises(exc):
                both_tsv(tmp_path, [bad], strict=strict)


# ---------------------------------------------------------------------------
# Container-level coverage
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("compression", ["none", "record", "block"])
def test_compression_layouts_parity(tmp_path, compression):
    rng = np.random.default_rng(3)
    records = []
    for i in range(200):
        targets = [f"http://t{rng.integers(0, 300)}/"
                   for _ in range(rng.integers(0, 8))]
        records.append((f"http://u{rng.integers(0, 120)}/", meta(targets)))
    py, nat = both_seqfile(tmp_path, records, compression=compression)
    assert_same(py, nat)


def test_multifile_segment_order_parity(tmp_path):
    """Ids depend on record order across files; the native path must
    walk files in the same listing order as the Python path."""
    seg = tmp_path / "seg"
    seg.mkdir()
    rng = np.random.default_rng(5)
    for i in range(7):
        records = [
            (f"http://u{rng.integers(0, 40)}/",
             meta([f"http://t{rng.integers(0, 80)}/"
                   for _ in range(rng.integers(0, 5))]))
            for _ in range(30)
        ]
        write_sequence_file(str(seg / f"metadata-{i:05d}"), records)
    py = load_crawl_seqfile(str(seg), native="off")
    nat = load_crawl_seqfile(str(seg), native="auto")
    assert_same(py, nat)


def test_invalid_utf8_replacement_parity(tmp_path):
    """Text payloads are decoded with errors='replace'; the native
    decoder must produce CPython's maximal-subpart U+FFFD placement."""
    bad_urls = [
        b"http://x/\xff\xfe",          # invalid leads
        b"http://y/\xc2",              # truncated 2-byte at end
        b"http://z/\xe0\xa0",          # truncated 3-byte
        b"http://w/\xe0\x80\x80",      # overlong -> 3 replacements
        b"http://v/\xed\xa0\x80",      # surrogate bytes -> 3 replacements
        b"http://u/\xf0\x9f\x98\x80ok",  # valid 4-byte passes
        b"http://t/\xf4\x90\x80\x80",  # beyond U+10FFFF
        b"http://s/\x80tail",          # stray continuation
    ]
    # Hand-assemble an uncompressed v6 SequenceFile with raw key bytes
    # (write_sequence_file only takes str).
    def text_bytes(payload: bytes) -> bytes:
        assert len(payload) < 112
        return struct.pack("b", len(payload)) + payload

    cls = b"org.apache.hadoop.io.Text"
    p = str(tmp_path / "rawseq")
    sync = bytes(range(16))
    with open(p, "wb") as f:
        f.write(b"SEQ\x06")
        f.write(struct.pack("b", len(cls)) + cls)
        f.write(struct.pack("b", len(cls)) + cls)
        f.write(b"\x00\x00")
        f.write(struct.pack(">i", 0))
        f.write(sync)
        for url in bad_urls:
            k = text_bytes(url)
            v = text_bytes(json.dumps(
                {"content": {"links": [{"type": "a", "href": "t"}]}}
            ).encode())
            f.write(struct.pack(">i", len(k) + len(v)))
            f.write(struct.pack(">i", len(k)))
            f.write(k + v)
    py = load_crawl_seqfile(p, native="off")
    nat = load_crawl_seqfile(p, native="auto")
    assert_same(py, nat)
    assert any("�" in nm for nm in py[1].names)


def test_randomized_fuzz_parity(tmp_path):
    """Broad randomized differential sweep over value shapes."""
    rng = np.random.default_rng(11)
    pool_strings = ADVERSARIAL_HREFS + ["http://t/", "x", "ümläut"]

    def rand_value(depth=0):
        k = rng.integers(0, 9 if depth < 3 else 6)
        if k == 0:
            return pool_strings[rng.integers(0, len(pool_strings))]
        if k == 1:
            return int(rng.integers(-10**9, 10**9))
        if k == 2:
            return float(rng.standard_normal() * 10.0 ** rng.integers(-20, 20))
        if k == 3:
            return bool(rng.integers(0, 2))
        if k == 4:
            return None
        if k == 5:
            return int(rng.integers(0, 10)) * 10**18  # big ints
        if k == 6:
            return [rand_value(depth + 1) for _ in range(rng.integers(0, 4))]
        return {
            f"k{rng.integers(0, 5)}": rand_value(depth + 1)
            for _ in range(rng.integers(0, 4))
        }

    records = []
    for i in range(300):
        links = []
        for _ in range(rng.integers(0, 6)):
            entry = {}
            if rng.random() < 0.9:
                entry["type"] = "a" if rng.random() < 0.7 else rand_value()
            if rng.random() < 0.9:
                entry["href"] = rand_value()
            links.append(entry if rng.random() < 0.9 else rand_value())
        doc = {"content": {"links": links}}
        if rng.random() < 0.1:
            doc = rand_value()
        records.append(
            (f"http://u{rng.integers(0, 100)}/",
             json.dumps(doc, ensure_ascii=False))
        )
    py, nat = both_seqfile(tmp_path, records, strict=False,
                           compression="block")
    assert_same(py, nat)


def test_container_error_class_parity(tmp_path):
    """Container-level failures must raise the same exception CLASSES as
    the Python reader: EOFError for truncation, zlib.error for corrupt
    deflate, ValueError for structural garbage."""
    import zlib

    p = str(tmp_path / "seg")
    write_sequence_file(p, [("http://a/", meta(["http://b/"]))] * 5)
    whole = open(p, "rb").read()
    # truncation mid-record -> EOFError on both paths
    trunc = str(tmp_path / "trunc")
    with open(trunc, "wb") as f:
        f.write(whole[:-7])
    for native_mode in ("off", "auto"):
        with pytest.raises(EOFError):
            load_crawl_seqfile(trunc, native=native_mode)
    # corrupt deflate stream -> zlib.error on both paths
    pr = str(tmp_path / "rec")
    write_sequence_file(pr, [("http://a/", meta(["http://b/"]))],
                        compression="record")
    data = bytearray(open(pr, "rb").read())
    data[-3] ^= 0xFF  # flip a byte inside the record's zlib stream
    bad = str(tmp_path / "badz")
    with open(bad, "wb") as f:
        f.write(bytes(data))
    for native_mode in ("off", "auto"):
        with pytest.raises(zlib.error):
            load_crawl_seqfile(bad, native=native_mode)
    # structural garbage -> ValueError on both paths
    garb = str(tmp_path / "garb")
    with open(garb, "wb") as f:
        f.write(b"SEQ\x07" + whole[4:])
    for native_mode in ("off", "auto"):
        with pytest.raises(ValueError):
            load_crawl_seqfile(garb, native=native_mode)


def test_jsonl_nonstring_url_falls_back(tmp_path):
    """A non-string JSONL url is valid for the Python path (the parsed
    value becomes the id-map key); the native path can't represent it
    and must fall back — same result either way."""
    p = str(tmp_path / "crawl.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps({"url": 5, "metadata": {"content": {"links": [
            {"type": "a", "href": "http://t/"}]}}}) + "\n")
    g1, im1 = load_crawl_file(p, native="off")
    g2, im2 = load_crawl_file(p, native="auto")
    assert im1.names == im2.names == [5, "http://t/"]
    np.testing.assert_array_equal(g1.src, g2.src)
    np.testing.assert_array_equal(g1.dst, g2.dst)


def test_explicit_workers_selects_python_pool(tmp_path, monkeypatch):
    """An explicit workers= request is a request for the Python pool;
    the native path must not override it (VERDICT-class regression:
    --ingest-workers N silently ignored)."""
    p = str(tmp_path / "seg")
    write_sequence_file(p, [("http://a/", meta(["http://b/"]))])

    def boom(*a, **k):
        raise AssertionError("native path used despite explicit workers")

    monkeypatch.setattr(native, "crawl_load", boom)
    g, im = load_crawl_seqfile(p, workers=1)  # explicit -> python path
    assert im.names == ["http://a/", "http://b/"]


def test_mutation_fuzz_parity(tmp_path):
    """Random byte mutations of valid records: the canonical-JSON fuzz
    above never exercises malformed documents, so corrupt the text and
    require both paths to agree — same graph in non-strict mode, same
    exception class in strict mode."""
    rng = np.random.default_rng(23)
    base = ('{"content": {"links": [{"type": "a", "href": "http://t1/"}, '
            '{"type": "a", "href": "http://t2/\\u00e9"}, '
            '{"type": "b", "href": 3.5}]}}')
    alphabet = list('{}[]",:\\ au0xe9' + "\x01\x1f")
    for trial in range(120):
        doc = list(base)
        for _ in range(int(rng.integers(1, 4))):
            pos = int(rng.integers(0, len(doc)))
            op = rng.integers(0, 3)
            if op == 0:
                doc[pos] = alphabet[int(rng.integers(0, len(alphabet)))]
            elif op == 1:
                doc.insert(pos, alphabet[int(rng.integers(0, len(alphabet)))])
            else:
                del doc[pos]
        mutated = "".join(doc)
        records = [("http://ok/", meta(["http://x/"])),
                   ("http://mut/", mutated)]
        p = str(tmp_path / f"seg{trial}")
        write_sequence_file(p, records)
        # non-strict: identical graphs
        py = load_crawl_seqfile(p, strict=False, native="off")
        nat = load_crawl_seqfile(p, strict=False, native="auto")
        assert_same(py, nat)
        # strict: same outcome (success with identical graphs, or the
        # same exception class)
        try:
            py_s = load_crawl_seqfile(p, strict=True, native="off")
            py_exc = None
        except Exception as e:  # noqa: BLE001 - class parity is the point
            py_s, py_exc = None, type(e)
        try:
            nat_s = load_crawl_seqfile(p, strict=True, native="auto")
            nat_exc = None
        except Exception as e:  # noqa: BLE001
            nat_s, nat_exc = None, type(e)
        assert py_exc == nat_exc, (mutated, py_exc, nat_exc)
        if py_exc is None:
            assert_same(py_s, nat_s)


def test_container_mutation_fuzz_parity(tmp_path):
    """Random byte corruptions of the CONTAINER (all three compression
    layouts): both paths must agree on the result or the exception
    class. This sweep caught three real bugs at larger trial counts —
    a Python-side MemoryError on corrupt length fields (huge upfront
    allocation, now bounded in _read_exact), an int32 overflow in the
    native metadata loop, and native validation firing at an earlier
    stage than the Python reader (class names / block-codec check)."""
    rng = np.random.default_rng(29)
    bases = {}
    for comp in ("none", "record", "block"):
        p = str(tmp_path / f"base-{comp}")
        write_sequence_file(
            p,
            [(f"u{i}", meta([f"t{j}" for j in range(i % 4)]))
             for i in range(12)],
            compression=comp, sync_every=5,
        )
        bases[comp] = open(p, "rb").read()

    def norm(e):
        # UnicodeDecodeError (strict header-class decode in Python) is
        # a ValueError subclass — the same catchable class
        return "ValueError" if isinstance(e, UnicodeDecodeError) \
            else type(e).__name__

    p = str(tmp_path / "mut")
    for trial in range(150):
        comp = ("none", "record", "block")[trial % 3]
        data = bytearray(bases[comp])
        for _ in range(int(rng.integers(1, 5))):
            op = rng.integers(0, 3)
            pos = int(rng.integers(0, len(data)))
            if op == 0:
                data[pos] = int(rng.integers(0, 256))
            elif op == 1:
                data.insert(pos, int(rng.integers(0, 256)))
            else:
                del data[pos]
        with open(p, "wb") as f:
            f.write(bytes(data))
        for strict in (False, True):
            def run(native_mode):
                try:
                    g, im = load_crawl_seqfile(p, strict=strict,
                                               native=native_mode)
                    return (im.names, g.src.tolist(), g.dst.tolist())
                except Exception as e:  # noqa: BLE001 - class parity
                    return norm(e)
            r1, r2 = run("off"), run("auto")
            assert r1 == r2, (trial, strict, str(r1)[:80], str(r2)[:80])


def test_threaded_ingest_order_identity(tmp_path):
    """crawl_load with C++ worker threads must produce byte-identical
    ids/edges to the serial path at any thread count (file-ordered
    interning — the same contract the Python process pool keeps)."""
    seg = tmp_path / "seg"
    seg.mkdir()
    rng = np.random.default_rng(17)
    for i in range(11):  # odd count: exercises partial windows
        records = []
        for _ in range(25):
            targets = [f"http://t{rng.integers(0, 90)}/"
                       for _ in range(rng.integers(0, 6))]
            records.append(
                (f"http://u{rng.integers(0, 50)}/", meta(targets)))
        write_sequence_file(str(seg / f"metadata-{i:05d}"), records,
                            compression="block")
    paths = [str(seg / f"metadata-{i:05d}") for i in range(11)]
    g1, im1 = native.crawl_load(paths, "seqfile", threads=1)
    for nthreads in (2, 4, 16):
        g2, im2 = native.crawl_load(paths, "seqfile", threads=nthreads)
        assert im1.names == im2.names
        np.testing.assert_array_equal(g1.src, g2.src)
        np.testing.assert_array_equal(g1.dst, g2.dst)
        np.testing.assert_array_equal(g1.dangling_mask, g2.dangling_mask)
    # and identical to the pure-Python path
    py_g, py_im = load_crawl_seqfile(str(seg), native="off")
    assert py_im.names == im1.names
    np.testing.assert_array_equal(py_g.src, g1.src)


def test_threaded_ingest_earliest_error_wins(tmp_path):
    """With threads, a strict error must surface from the EARLIEST
    failing file in input order (serial-walk semantics), not whichever
    worker fails first."""
    seg = tmp_path / "seg"
    seg.mkdir()
    for i in range(8):
        if i == 3:
            recs = [("http://bad3/", "{broken")]
        elif i == 6:
            recs = [("http://bad6/", '{"content": {"links": [{"href": "x"}]}}')]
        else:
            recs = [(f"http://ok{i}/", meta(["http://t/"]))]
        write_sequence_file(str(seg / f"metadata-{i:05d}"), recs)
    paths = [str(seg / f"metadata-{i:05d}") for i in range(8)]
    # file 3 (JSONDecodeError) must win over file 6 (KeyError), and the
    # error must name the culprit file, not the batch
    with pytest.raises(json.JSONDecodeError, match="metadata-00003"):
        native.crawl_load(paths, "seqfile", strict=True, threads=4)
    with pytest.raises(json.JSONDecodeError, match="metadata-00003"):
        native.crawl_load(paths, "seqfile", strict=True, threads=1)
    # non-strict: bad3's record is kept with no targets; bad6 still
    # raises KeyError?  No — non-strict skips entries, so it loads.
    g, im = native.crawl_load(paths, "seqfile", strict=False, threads=4)
    py = load_crawl_seqfile(str(seg), strict=False, native="off")
    assert im.names == py[1].names


def test_cli_uses_native_path(tmp_path, capsys):
    """The CLI seqfile route goes through load_crawl_seqfile, which now
    prefers the native parser — end result identical either way."""
    from pagerank_tpu.cli import main

    p = str(tmp_path / "seg")
    write_sequence_file(
        p,
        [("http://a/", meta(["http://b/"])),
         ("http://b/", meta(["http://a/", "http://c/"]))],
    )
    out = str(tmp_path / "r.tsv")
    rc = main(["--input", p, "--iters", "3", "--engine", "cpu",
               "--out", out, "--log-every", "0"])
    assert rc == 0
    with open(out) as f:
        ranks = dict(line.split("\t") for line in f.read().splitlines())
    assert set(ranks) == {"http://a/", "http://b/", "http://c/"}
