"""Sparse boundary exchange (ISSUE 8; parallel/partition.build_halo_plan
+ engines/jax_engine._setup_vs_halo; docs/PERF_NOTES.md "Sparse
boundary exchange").

Three layers, all on the 8-fake-device CPU mesh:

- the HALO BUILDER against a numpy reference: per-device read sets
  decoded independently from the packed slot tables on random AND
  R-MAT graphs, table consistency (send rows == receive rows, pads
  inert), full coverage (every remote read is head-replicated or
  arrives in exactly one round), and write-band windows covering every
  (writer, owner) overlap;
- STEP PARITY vs the dense psum_scatter path: the gather inputs are
  bit-identical by construction, so full runs must agree to (at most)
  contribution-merge regrouping — pinned bit-exact where the dense
  mode itself is deterministic;
- the COMMS accounting: model-minimizing head K, counter accumulation,
  and the comms.*/elastic.* names visible through the Prometheus
  exporter (ROADMAP [scale] leftover).
"""

import numpy as np
import pytest

import jax

from pagerank_tpu import JaxTpuEngine, PageRankConfig, build_graph
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.parallel import partition
from pagerank_tpu.utils.synth import rmat_edges

NDEV = len(jax.devices())

needs_mesh = pytest.mark.skipif(NDEV < 8, reason="needs 8 fake devices")


def _random_graph(n=512, e=4096, seed=0):
    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


def _rmat_graph(scale=11, ef=8, seed=1):
    src, dst = rmat_edges(scale, edge_factor=ef, seed=seed)
    return build_graph(src, dst, n=1 << scale)


def _cfg(**kw):
    base = dict(num_iters=8, dtype="float32", accum_dtype="float32",
                num_devices=min(8, NDEV), vertex_sharded=True)
    base.update(kw)
    return PageRankConfig(**base)


def _halo_engine(graph, **kw):
    return JaxTpuEngine(_cfg(halo_exchange=True, **kw)).build(graph)


# -- halo builder vs numpy reference ---------------------------------------


def _reference_read_sets(src_host, ndev, sz, group):
    """Independent decode of each device's read set: a plain python
    loop over every slot word (the oracle the vectorized builder is
    checked against)."""
    log2g = group.bit_length() - 1
    out = [set() for _ in range(ndev)]
    for s, ss in enumerate(src_host):
        rows = ss.shape[0]
        rpd = rows // ndev
        for d in range(ndev):
            for w in np.asarray(ss[d * rpd:(d + 1) * rpd]).reshape(-1):
                local = int(w) >> log2g
                if local < sz:
                    out[d].add(s * sz + local)
    return [np.array(sorted(x), np.int64) for x in out]


@needs_mesh
@pytest.mark.parametrize("graph_fn", [_random_graph, _rmat_graph])
def test_read_sets_match_numpy_reference(graph_fn):
    eng = _halo_engine(graph_fn())
    plan = eng._halo_plan
    sz = eng._layout["stripe_span"]
    group = eng._layout["group"]
    src_host = [np.asarray(jax.device_get(s)) for s in eng._src]
    got = partition.device_read_sets(
        src_host, ndev=plan.ndev, sz=sz, group=group
    )
    want = _reference_read_sets(src_host, plan.ndev, sz, group)
    for d in range(plan.ndev):
        np.testing.assert_array_equal(got[d], want[d])


@needs_mesh
@pytest.mark.parametrize("graph_fn", [_random_graph, _rmat_graph])
def test_halo_tables_cover_every_remote_read_exactly_once(graph_fn):
    """Coverage + consistency: every remote read id is either in the
    replicated head or arrives in EXACTLY one round's receive row; the
    sender's local indices match the receiver's global ids; pads are
    inert (send pad = blk zero slot, recv pad = n_vs trash)."""
    eng = _halo_engine(graph_fn())
    plan = eng._halo_plan
    ndev, blk, n_vs, K = plan.ndev, plan.blk, plan.n_vs, plan.head_k
    sz = eng._layout["stripe_span"]
    group = eng._layout["group"]
    src_host = [np.asarray(jax.device_get(s)) for s in eng._src]
    reads = partition.device_read_sets(
        src_host, ndev=ndev, sz=sz, group=group
    )
    recv_by_dev = [[] for _ in range(ndev)]
    for rnd, send, recv in zip(plan.read_rounds, plan.send_idx,
                               plan.recv_ids):
        assert send.shape == recv.shape == (ndev, rnd.width)
        senders = {s: t for s, t in rnd.perm}
        for d in range(ndev):
            row = recv[d][recv[d] < n_vs]
            # A device with no inbound link this round receives only
            # zeros — its recv row must be all-trash.
            src_dev = (d - rnd.offset) % ndev
            if senders.get(src_dev) != d:
                assert row.size == 0
                continue
            # Receiver's global ids == sender's local ids + owner base.
            srow = send[src_dev][send[src_dev] < blk]
            np.testing.assert_array_equal(
                row, srow.astype(np.int64) + src_dev * blk
            )
            # Tail only: never own-block, never head.
            assert np.all(row // blk == src_dev) and src_dev != d
            assert np.all(row >= K)
            recv_by_dev[d].append(row)
    for d in range(ndev):
        got = (np.concatenate(recv_by_dev[d]) if recv_by_dev[d]
               else np.zeros(0, np.int64))
        # Exactly once: no duplicates across rounds.
        assert np.unique(got).size == got.size
        want = reads[d]
        want = want[(want // blk != d) & (want >= K)]
        np.testing.assert_array_equal(np.sort(got), want)


@needs_mesh
def test_write_windows_cover_every_band_overlap():
    """Every (writer, owner) overlap of a device's contribution band
    must be covered by exactly one round's window: start at the
    overlap's low end, width >= the overlap, landing at the owner's
    matching local offset."""
    eng = _halo_engine(_rmat_graph())
    plan = eng._halo_plan
    ndev, blk, n_vs = plan.ndev, plan.blk, plan.n_vs
    rk_host = [np.asarray(jax.device_get(r)) for r in eng._row_block]
    # Recompute bands from the engine's own placed tables (the present
    # ids ride at the tail of the contrib args, after the halo tables).
    ids_host = []
    n_halo = 2 * len(plan.read_rounds) + 2 * len(plan.write_rounds)
    stripe_args = eng._contrib_args[n_halo:]
    for s in range(len(eng._src)):
        ids_host.append(np.asarray(jax.device_get(stripe_args[3 * s + 2])))
    bands = partition.device_write_bands(
        rk_host, ids_host, ndev=ndev, n_vs=n_vs
    )
    rounds = {r.offset: (r, ws, wr) for r, ws, wr in
              zip(plan.write_rounds, plan.wsend_start, plan.wrecv_start)}
    for d, (lo, hi) in enumerate(bands):
        for p in range(ndev):
            if p == d:
                continue
            s_lo, s_hi = max(lo, p * blk), min(hi, (p + 1) * blk)
            if s_lo >= s_hi:
                continue
            rnd, ws, wr = rounds[p - d]
            assert (d, p) in rnd.perm
            assert ws[d] == s_lo
            assert rnd.width >= s_hi - s_lo
            assert wr[p] == s_lo - p * blk


@needs_mesh
def test_auto_head_k_minimizes_model():
    """The model-driven K rule: the auto K's modeled bytes are <= the
    no-replication plan's and <= a sampled explicit alternative's."""
    g = _rmat_graph(scale=12, ef=8, seed=2)
    auto = _halo_engine(g)
    k0 = _halo_engine(g, halo_head=0)
    alt = _halo_engine(g, halo_head=4096)
    b_auto = auto._halo_plan.sparse_bytes_per_iter()
    assert b_auto <= k0._halo_plan.sparse_bytes_per_iter()
    assert b_auto <= alt._halo_plan.sparse_bytes_per_iter()
    # And the sparse model must beat the dense exchange on a power-law
    # graph at this geometry (the whole point).
    assert b_auto < auto._halo_plan.dense_bytes_per_iter()


# -- step parity vs the dense psum_scatter path ----------------------------


@needs_mesh
@pytest.mark.parametrize("graph_fn", [_random_graph, _rmat_graph])
def test_full_run_matches_dense_exchange_f32(graph_fn):
    """f32 full runs: the gather inputs are bit-identical and the f32
    round absorbs merge regrouping — bit-equal ranks (the same
    contract the dense vertex-sharded mode holds vs replicated)."""
    g = graph_fn()
    r_dense = JaxTpuEngine(_cfg()).build(g).run()
    r_halo = _halo_engine(g).run()
    np.testing.assert_array_equal(r_halo, r_dense)


@needs_mesh
def test_full_run_matches_dense_exchange_pair_striped():
    """The striped pair layout (f32 storage, pair-f64 accumulation)
    through the halo exchange vs the dense path."""
    class _TinyStripes(JaxTpuEngine):
        def _stripe_max(self):
            return 256

        def _stripe_target(self):
            return 256

    g = _rmat_graph(scale=10)
    cfg = _cfg(accum_dtype="float64", wide_accum="pair", num_iters=4)
    r_dense = _TinyStripes(cfg).build(g).run_fast()
    eng = _TinyStripes(cfg.replace(halo_exchange=True)).build(g)
    assert eng.layout_info()["form"] == "vs_halo"
    assert len(eng._src) > 1  # really striped
    np.testing.assert_allclose(
        np.float64(eng.run_fast()), np.float64(r_dense),
        rtol=1e-6, atol=1e-12,
    )


@needs_mesh
def test_fused_and_probed_forms_match_stepwise():
    g = _rmat_graph()
    r_step = _halo_engine(g).run_fast()
    fused = _halo_engine(g)
    np.testing.assert_array_equal(fused.run_fused(), r_step)
    probed = _halo_engine(g, probe_every=2)
    r_p = probed.run()
    np.testing.assert_array_equal(r_p, r_step)


@needs_mesh
def test_f64_storage_matches_dense_to_rounding():
    g = _rmat_graph(scale=10)
    cfg = _cfg(dtype="float64", accum_dtype="float64", num_iters=6)
    r_dense = JaxTpuEngine(cfg).build(g).run_fast()
    r_halo = JaxTpuEngine(
        cfg.replace(halo_exchange=True)
    ).build(g).run_fast()
    # Only the contribution merge may regroup (<= 1 ulp/iteration).
    np.testing.assert_array_almost_equal_nulp(r_halo, r_dense, nulp=8)


@needs_mesh
def test_snapshot_resume_roundtrip(tmp_path):
    from pagerank_tpu.utils.snapshot import Snapshotter, resume_engine

    g = _rmat_graph()
    eng = _halo_engine(g)
    eng.run_fast(num_iters=3)
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference",
                       mesh_meta=eng.snapshot_meta())
    snap.save(3, eng.ranks())
    e2 = _halo_engine(g)
    assert resume_engine(e2, snap) == 3
    np.testing.assert_array_equal(e2.ranks(), eng.ranks())
    r_full = e2.run_fast()
    np.testing.assert_array_equal(r_full, eng.run_fast())


# -- downgrades + validation -----------------------------------------------


@needs_mesh
def test_multi_dispatch_layout_downgrades_to_dense():
    class _TinyScan(JaxTpuEngine):
        def _stripe_max(self):
            return 256

        def _stripe_target(self):
            return 256

        SCAN_STRIPE_UNITS = 0

    g = _rmat_graph(scale=10)
    eng = _TinyScan(_cfg(halo_exchange=True)).build(g)
    info = eng.layout_info()
    assert info["form"] == "vs_multi_dispatch"
    assert info["halo"] == "off:multi_dispatch"
    assert eng._halo_plan is None
    r = eng.run_fast()
    r_dense = _TinyScan(_cfg()).build(g).run_fast()
    np.testing.assert_array_equal(r, r_dense)


def test_config_validation():
    with pytest.raises(ValueError, match="requires vertex_sharded"):
        PageRankConfig(halo_exchange=True).validate()
    with pytest.raises(ValueError, match="vs_bounded"):
        PageRankConfig(vertex_sharded=True, vs_bounded=True,
                       halo_exchange=True).validate()
    with pytest.raises(ValueError, match="halo_head"):
        PageRankConfig(halo_head=-2).validate()
    PageRankConfig(vertex_sharded=True, halo_exchange=True,
                   halo_head=256).validate()


def test_single_device_halo_is_exact_and_silent():
    g = _random_graph()
    cfg = _cfg(num_devices=1, halo_exchange=True)
    eng = JaxTpuEngine(cfg).build(g)
    plan = eng._halo_plan
    assert plan.ndev == 1 and not plan.read_rounds \
        and not plan.write_rounds
    assert eng.comms_model()["bytes_per_iter"] == 0
    r = eng.run_fast()
    r_dense = JaxTpuEngine(
        _cfg(num_devices=1)
    ).build(g).run_fast()
    np.testing.assert_array_equal(r, r_dense)


# -- comms accounting + exporter wiring ------------------------------------


@needs_mesh
def test_comms_counter_accumulates_per_iteration():
    obs_metrics.get_registry().reset()
    g = _rmat_graph()
    eng = _halo_engine(g)
    per = eng.comms_model()["bytes_per_iter"]
    assert per > 0
    ctr = obs_metrics.counter("comms.bytes_exchanged")
    c0 = ctr.value
    eng.run_fast(num_iters=5)
    assert ctr.value - c0 == 5 * per
    # Fused dispatch counts the same model per iteration.
    e2 = _halo_engine(g)
    c1 = ctr.value
    e2.run_fused(num_iters=4)
    assert ctr.value - c1 == 4 * e2.comms_model()["bytes_per_iter"]
    # Probed iterations count too (step_probed's single-program
    # branch dispatches outside _device_step).
    e3 = _halo_engine(g, probe_every=2)
    c2 = ctr.value
    e3.run()
    assert ctr.value - c2 == 8 * e3.comms_model()["bytes_per_iter"]


@needs_mesh
def test_dense_mode_reports_comms_model_too():
    g = _rmat_graph()
    eng = JaxTpuEngine(_cfg()).build(g)
    cm = eng.comms_model()
    assert cm["mode"] == "dense" and cm["bytes_per_iter"] > 0
    assert cm["sparse_bytes_per_iter"] is None
    # Replicated forms have no per-vertex exchange to model.
    rep = JaxTpuEngine(
        PageRankConfig(num_iters=2, num_devices=min(8, NDEV))
    ).build(g)
    assert rep.comms_model() is None


@needs_mesh
def test_watchdog_heartbeats_through_sparse_path():
    """ROADMAP [scale] leftover: an armed stall watchdog receives one
    heartbeat per completed sparse-exchange step (engine.run's feed),
    so a wedged halo solve is diagnosable like every other form."""
    from pagerank_tpu.obs import live as obs_live

    wd = obs_live.StallWatchdog(timeout_s=600.0,
                                interrupt=lambda: None)
    obs_live.arm_watchdog(wd)
    try:
        eng = _halo_engine(_rmat_graph(), num_iters=4)
        eng.run()
    finally:
        obs_live.disarm_watchdog()
    # engine.run feeds the 0-based iteration BEFORE the counter
    # advances — the final heartbeat of a 4-iteration run carries 3.
    assert wd.last_iteration == 3
    assert wd.stalls == 0


@needs_mesh
def test_cost_reports_cover_sparse_step():
    """The XLA cost ledger harvests the vs_halo step program like any
    single-program form (bench legs embed it per leg)."""
    from pagerank_tpu.obs import costs as obs_costs

    obs_costs.reset()
    eng = _halo_engine(_rmat_graph())
    reports = eng.cost_reports()
    assert "step" in reports
    assert reports["step"]["peak_bytes"] is None \
        or reports["step"]["peak_bytes"] > 0


@needs_mesh
def test_comms_and_elastic_metrics_visible_in_exporter():
    """ROADMAP [scale] leftover: comms.* and elastic.* instruments
    render through the Prometheus exporter during a sharded
    sparse-exchange solve."""
    from pagerank_tpu.obs.live import render_prometheus
    from pagerank_tpu.parallel.elastic import DeviceHealthMonitor

    obs_metrics.get_registry().reset()
    g = _rmat_graph()
    eng = _halo_engine(g)
    DeviceHealthMonitor()  # registers the elastic straggler gauges
    eng.run_fast(num_iters=3)
    text = render_prometheus()
    for name in ("comms_bytes_exchanged", "comms_bytes_per_iter",
                 "comms_dense_bytes_per_iter", "comms_halo_fraction",
                 "comms_head_k", "elastic_straggler_skew"):
        assert name in text, name
