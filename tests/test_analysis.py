"""The analysis subsystem (pagerank_tpu/analysis): AST lint rules, the
jaxpr contract suite over every engine dispatch form, the CLI contract
(exit codes, JSON schema, allowlist), and regression fixtures proving
each rule catches the defect class it was written for."""

import functools
import json
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pagerank_tpu.analysis import load_allowlist, split_allowlisted
from pagerank_tpu.analysis.__main__ import main as analysis_main
from pagerank_tpu.analysis import contracts as contracts_mod
from pagerank_tpu.analysis import lint as lint_mod


def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def _rules_of(findings):
    return {f.rule for f in findings}


# -- lint rules on seeded fixtures -----------------------------------------

FIXTURES = {
    "PTL001": """
        def f(ids, table):
            return (table[ids >> 7] << 7) | (ids & 127)
    """,
    "PTL002": """
        import jax.numpy as jnp

        def f(n):
            return jnp.zeros(n)
    """,
    "PTL003": """
        import jax

        @jax.jit
        def f(x):
            print(x)
            return x.item()
    """,
    "PTL004": """
        def f(x, acc=[]):
            acc.append(x)
            return acc
    """,
    "PTL005": """
        import numpy as np

        def f(x):
            return np.asarray(x, dtype=np.float64)
    """,
    "PTL006": """
        def f(x):
            try:
                return x()
            except Exception:
                pass
    """,
    "PTL007": """
        import sys

        def f(msg):
            print(msg)
            sys.stderr.write(msg)
    """,
    "PTL008": """
        import atexit
        import signal

        def f(handler):
            signal.signal(signal.SIGTERM, handler)
            atexit.register(handler)
    """,
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_seeded_violation_fires_expected_rule(tmp_path, rule):
    path = _write(tmp_path, f"bad_{rule.lower()}.py", FIXTURES[rule])
    findings = lint_mod.lint_file(path)
    assert rule in _rules_of(findings), findings


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_cli_exits_nonzero_per_rule(tmp_path, capsys, rule):
    path = _write(tmp_path, f"bad_{rule.lower()}.py", FIXTURES[rule])
    rc = analysis_main([path, "--lint-only", "--allowlist", "none", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert not out["ok"]
    assert rule in {f["rule"] for f in out["findings"]}


def test_ell_deal_regression_fixture(tmp_path):
    """The exact pre-fix ops/ell.py:254 deal composition (hardcoded
    >> 7 / << 7 / & 127 lane geometry — ADVICE r5) must trip PTL001;
    the landed LANES-derived fix must not."""
    bad = _write(tmp_path, "deal_old.py", """
        import numpy as np

        def compose(new_of_old, n, order):
            ids = np.arange(n, dtype=np.int64)
            new_pos = (new_of_old[ids >> 7] << 7) | (ids & 127)
            dealt = np.empty(n, order.dtype)
            dealt[new_pos] = order
            return dealt
    """)
    findings = lint_mod.lint_file(bad)
    assert [f.rule for f in findings].count("PTL001") >= 3

    fixed = _write(tmp_path, "deal_new.py", """
        import numpy as np
        LANES = 128

        def compose(new_of_old, n, order):
            ids = np.arange(n, dtype=np.int64)
            new_pos = new_of_old[ids // LANES] * LANES + (ids % LANES)
            dealt = np.empty(n, order.dtype)
            dealt[new_pos] = order
            return dealt
    """)
    assert lint_mod.lint_file(fixed) == []


def test_ptl006_swallow_semantics(tmp_path):
    """PTL006 boundaries: bare except ALWAYS flags unless it re-raises;
    broad except flags only when the body is a pure swallow; narrow
    handlers and real handling never flag (the allowlist — not rule
    carve-outs — covers deliberate best-effort sites)."""
    flagged = _write(tmp_path, "swallows.py", """
        def a(x):
            try:
                return x()
            except:            # bare, no re-raise -> flag
                return None

        def b(x):
            try:
                return x()
            except BaseException:
                ...            # pure swallow -> flag
    """)
    findings = [f for f in lint_mod.lint_file(flagged) if f.rule == "PTL006"]
    assert len(findings) == 2, findings

    clean = _write(tmp_path, "handled.py", """
        def a(x):
            try:
                return x()
            except:            # bare but re-raises -> clean
                raise

        def b(x, log):
            try:
                return x()
            except Exception as e:   # broad but handled -> clean
                log(e)
                return None

        def c(x):
            try:
                return x()
            except KeyError:   # narrow swallow -> clean (deliberate)
                pass
    """)
    assert [f for f in lint_mod.lint_file(clean) if f.rule == "PTL006"] == []


def test_ptl007_scope_exempts_cli_entry_points(tmp_path):
    """PTL007 polices LIBRARY modules: a print/stderr-write flags under
    a library-relative path (and in fixture mode), but the CLI entry
    points — cli.py and any */__main__.py — are exempt by scope, and
    prints routed to an injectable stream still flag (the deliberate
    MetricsLogger stream is an allowlist entry, not a carve-out)."""
    p = _write(tmp_path, "prints.py", FIXTURES["PTL007"])

    def ptl007(rel):
        return [f for f in lint_mod.lint_file(p, rel)
                if f.rule == "PTL007"]

    assert len(ptl007(None)) == 2          # fixture mode: all rules
    assert len(ptl007("utils/foo.py")) == 2  # library module: flags
    assert ptl007("cli.py") == []            # CLI entry point: exempt
    assert ptl007("obs/__main__.py") == []   # module CLI: exempt
    assert ptl007("analysis/__main__.py") == []

    streamed = _write(tmp_path, "streamed.py", """
        def f(msg, stream):
            print(msg, file=stream)
    """)
    assert [f.rule for f in lint_mod.lint_file(streamed, "utils/m.py")
            if f.rule == "PTL007"] == ["PTL007"]


def test_ptl008_scope_exempts_supervisor_modules(tmp_path):
    """PTL008 (ISSUE 12): process-global handler installation flags in
    LIBRARY modules but is exempt in the two modules that OWN handlers
    — jobs.py (GracefulDrain) and cli.py (the entry point that installs
    it). An injectable-callback spelling (the GracefulDrain idiom:
    ``install=signal.signal`` as a default ARGUMENT, called through the
    parameter) never flags — only direct installation calls do."""
    p = _write(tmp_path, "handlers.py", FIXTURES["PTL008"])

    def ptl008(rel):
        return [f for f in lint_mod.lint_file(p, rel)
                if f.rule == "PTL008"]

    assert len(ptl008(None)) == 2            # fixture mode: all rules
    assert len(ptl008("utils/foo.py")) == 2  # library module: flags
    assert len(ptl008("parallel/elastic.py")) == 2
    assert ptl008("jobs.py") == []           # supervisor: exempt
    assert ptl008("cli.py") == []            # entry point: exempt

    injectable = _write(tmp_path, "drain.py", """
        import signal

        class Drain:
            def __init__(self, install=signal.signal):
                self._install = install

            def arm(self, signum, handler):
                self._install(signum, handler)
    """)
    assert [f for f in lint_mod.lint_file(injectable, "utils/m.py")
            if f.rule == "PTL008"] == []


def test_repo_tree_is_handler_free():
    """The PTL008 satellite's whole point, pinned: no library module in
    the shipped package installs signal/exit handlers (no waivers
    either — the allowlist carries no PTL008 entries)."""
    findings = [f for f in lint_mod.lint_tree() if f.rule == "PTL008"]
    assert findings == []


def test_lanes_assignment_is_the_one_allowed_spelling(tmp_path):
    p = _write(tmp_path, "geom.py", "LANES = 128\nHALF = 128 // 2\n")
    findings = lint_mod.lint_file(p)
    assert [f.line for f in findings if f.rule == "PTL001"] == [2]


def test_repo_ops_tree_has_no_lane_magic():
    """The satellite fix is load-bearing: the shipped ops/ tree must be
    PTL001-clean (LANES lives in ops/__init__ only)."""
    findings = [f for f in lint_mod.lint_tree() if f.rule == "PTL001"]
    assert findings == []


# -- allowlist -------------------------------------------------------------

def test_allowlist_waives_by_content_not_line(tmp_path):
    path = _write(tmp_path, "bad.py", FIXTURES["PTL004"])
    allow = tmp_path / "allow.txt"
    allow.write_text(
        "PTL004 | *bad.py | acc=[] | fixture demonstrates the waiver flow\n"
    )
    findings = lint_mod.lint_file(path)
    active, waived = split_allowlisted(findings, load_allowlist(str(allow)))
    assert [f.rule for f in active] == []
    assert len(waived) == 1


def test_allowlist_rejects_malformed_lines(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text("PTL004 | missing reason\n")
    with pytest.raises(ValueError):
        load_allowlist(str(allow))


def test_checked_in_allowlist_parses_and_every_entry_is_used():
    import os

    from pagerank_tpu.analysis import concurrency as conc_mod

    path = os.path.join(lint_mod.package_root(), "analysis", "allowlist.txt")
    waivers = load_allowlist(path)
    assert waivers, "the checked-in allowlist must carry the f64 waivers"
    # The full waivable surface: the lint pass, the concurrency (PTR)
    # pass, AND the kernel plane (PTK — its legacy-geometry waiver is
    # load-bearing, ISSUE 16) — a waiver either matches a live finding
    # in one of them or the fix landed and the entry is debt.
    from pagerank_tpu.analysis import kernels as kernels_mod

    findings = (lint_mod.lint_tree() + conc_mod.analyze_package()
                + kernels_mod.check_kernel_plane())
    _active, waived = split_allowlisted(findings, waivers)
    used = {id(w) for _f, w in waived}
    stale = [w for w in waivers if id(w) not in used]
    assert not stale, f"stale allowlist entries (fix landed?): {stale}"


# -- CLI contract on the real tree -----------------------------------------

def test_repo_tree_is_clean_lint_only(capsys):
    rc = analysis_main(["--lint-only", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"]
    assert out["findings"] == []
    assert out["counts"]["waived"] >= 5  # the checked-in f64 waivers


def test_explicit_in_package_file_keeps_scoping_and_allowlist(capsys):
    """An explicit path INSIDE the package must behave like the tree
    run: package-relative scoping, allowlist globs matching — not
    fixture mode (a regression would make `analysis ops/ell.py` fail
    on waived findings)."""
    import os

    target = os.path.join(lint_mod.package_root(), "ops", "ell.py")
    rc = analysis_main([target, "--lint-only", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0, out["findings"]
    assert out["counts"]["waived"] >= 3  # the f64 weight-plane waivers


def test_json_schema_is_stable(tmp_path, capsys):
    path = _write(tmp_path, "bad.py", FIXTURES["PTL002"])
    rc = analysis_main([path, "--lint-only", "--allowlist", "none", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert set(out) == {"version", "ok", "counts", "findings", "waived"}
    assert out["version"] == 1
    assert set(out["counts"]) == {"active", "waived"}
    f = out["findings"][0]
    assert set(f) == {"rule", "path", "line", "col", "message", "snippet"}


def test_list_rules(capsys):
    rc = analysis_main(["--list-rules"])
    text = capsys.readouterr().out
    assert rc == 0
    for rid in ("PTL001", "PTL002", "PTL003", "PTL004", "PTL005",
                "PTL006", "PTL007", "PTL008",
                "PTC001", "PTC002", "PTC003", "PTC004", "PTC005",
                "PTC006", "PTC007",
                "PTR001", "PTR002", "PTR003", "PTR004", "PTR005",
                "PTR006"):
        assert rid in text


# -- jaxpr contract suite (tier-1: every dispatch form) --------------------

_NDEV = min(2, len(jax.devices()))
_FORMS = {f.name: f for f in contracts_mod.engine_forms(_NDEV)}


@pytest.mark.parametrize("name", sorted(_FORMS))
def test_dispatch_form_contracts(name):
    findings = contracts_mod.check_engine_form(_FORMS[name])
    assert findings == [], [f.render() for f in findings]


def test_step_key_stability():
    findings = contracts_mod.check_step_key_stability(_NDEV)
    assert findings == [], [f.render() for f in findings]


def test_kernel_contracts():
    findings = contracts_mod.check_kernels()
    assert findings == [], [f.render() for f in findings]


def test_build_chain_contract_clean():
    """PTC006 on the real build chain: every restaged stage (plus the
    R-MAT generator) must stay 32-bit when abstract-evaled under
    x64."""
    findings = contracts_mod.check_build_chain()
    assert findings == [], [f.render() for f in findings]


def test_full_cli_run_is_clean(capsys):
    """The acceptance gate verbatim: `python -m pagerank_tpu.analysis`
    (lint + contracts, checked-in allowlist) exits 0 on the repo."""
    rc = analysis_main(["--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0, out["findings"]
    assert out["ok"]


# -- contract regressions: the checker catches the defect classes ----------

def test_contract_catches_f64_promotion(monkeypatch):
    """Seed the defect PTC002 exists for: a kernel helper that silently
    accumulates in f64 under an f32 config."""
    from pagerank_tpu.ops import spmv

    orig = spmv.dangling_mass
    monkeypatch.setattr(
        spmv, "dangling_mass",
        lambda r, dangling, accum_dtype=None: orig(r, dangling, jnp.float64),
    )
    findings = contracts_mod.check_engine_form(_FORMS["ell"])
    assert "PTC002" in _rules_of(findings), [f.render() for f in findings]


def test_contract_neutralizes_unconsumable_donation(monkeypatch):
    """Re-seed the defect PTC003 exists for: the r5 bench log's 'Some
    donated buffers were not usable' — the scatter stage donating
    per-edge buffers that can never alias its slot-plane outputs.
    Since r6 the stage-call boundary SELF-HEALS (the unconsumable
    donation is dropped before lowering — utils/compile_cache.
    usable_donations), so the seeded defect must produce NO warning
    and NO finding: the warning class is dead, not merely detected."""
    from pagerank_tpu.utils import compile_cache

    orig_call = compile_cache.stage_call
    seeded = {"hit": False}

    def bad_call(name, fn, args, **kw):
        if name == "scatter_slots":
            seeded["hit"] = True
            kw["donate_argnums"] = (0, 1, 2, 3)
        return orig_call(name, fn, args, **kw)

    monkeypatch.setattr(compile_cache, "stage_call", bad_call)
    compile_cache.clear_stage_cache()  # force a fresh (seeded) lowering
    try:
        findings = contracts_mod.check_engine_form(_FORMS["device_build"])
    finally:
        compile_cache.clear_stage_cache()  # drop the seeded executables
    assert seeded["hit"]
    assert "PTC003" not in _rules_of(findings), \
        [f.render() for f in findings]


def test_build_donation_check_catches_structural_defect(monkeypatch):
    """The structural half (r6, check_build_donations): a donating
    build stage whose outputs can no longer match the donated avals
    must FAIL analysis — here the sort stage is broken to emit int16
    keys, so its donated int32[e] inputs have no matching output."""
    import functools

    from pagerank_tpu.ops import device_build as db

    assert contracts_mod.check_build_donations() == []

    orig = db._relabel_sort

    def bad_sort(src, dst, inv_perm, *, n_padded, stripe_size):
        sb, ns = orig(src, dst, inv_perm, n_padded=n_padded,
                      stripe_size=stripe_size)
        return sb.astype(jnp.int16), ns.astype(jnp.int16)

    monkeypatch.setattr(db, "_relabel_sort", bad_sort)
    findings = contracts_mod.check_build_donations()
    assert "PTC003" in _rules_of(findings), [f.render() for f in findings]


def test_contract_catches_x64_widening(monkeypatch):
    """Seed the defect PTC006 exists for: the pre-restage relabel used
    ``jnp.argsort``, whose default iota payload silently widens to
    int64 once the pair-f64 config flips ``jax_enable_x64``."""
    from pagerank_tpu.ops import device_build as db

    def bad_relabel(in_degree):
        n = in_degree.shape[0]
        perm = jnp.argsort(-in_degree, stable=True).astype(jnp.int32)
        inv = jnp.zeros(n, jnp.int32).at[perm].set(
            jnp.arange(n, dtype=jnp.int32)
        )
        return perm, inv

    monkeypatch.setattr(db, "_relabel_perm", bad_relabel)
    findings = contracts_mod.check_build_chain()
    assert "PTC006" in _rules_of(findings), [f.render() for f in findings]


def test_contract_catches_host_callback(monkeypatch):
    """Seed the defect PTC005 exists for: a debug print smuggled into
    the traced step."""
    from pagerank_tpu.ops import spmv

    orig = spmv.dangling_mass

    def noisy(r, dangling, accum_dtype=None):
        jax.debug.print("mass step")
        return orig(r, dangling, accum_dtype)

    monkeypatch.setattr(spmv, "dangling_mass", noisy)
    findings = contracts_mod.check_engine_form(_FORMS["ell"])
    assert "PTC005" in _rules_of(findings), [f.render() for f in findings]


def test_device_build_emits_no_donation_warning():
    """The fixed build chain must be warning-free end to end (the
    contract the bench log violated). Shapes covered: the plain form
    AND the multichip dryrun's grouped+striped presentinel geometry
    (group=4, stripe_size=128, with_weights=False, 4096 raw edges —
    the exact dispatch whose residual "int32[4096], int32[4096],
    int8[4096]" warning the MULTICHIP_r05 tail showed; ISSUE 5
    satellite)."""
    import warnings

    from pagerank_tpu.ops import device_build as db

    rng = np.random.default_rng(7)
    with warnings.catch_warnings(record=True) as wlog:
        warnings.simplefilter("always")
        for with_w in (False, True):
            db.build_ell_device(
                jnp.asarray(rng.integers(0, 300, 2048), jnp.int32),
                jnp.asarray(rng.integers(0, 300, 2048), jnp.int32),
                n=300, with_weights=with_w,
            )
        db.build_ell_device(
            jnp.asarray(rng.integers(0, 256, 4096), jnp.int32),
            jnp.asarray(rng.integers(0, 256, 4096), jnp.int32),
            n=256, group=4, stripe_size=128, with_weights=False,
        )
    bad = [w for w in wlog if "donated buffers" in str(w.message)]
    assert bad == []
