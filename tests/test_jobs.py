"""Preemption-safe resumable jobs (ISSUE 12; pagerank_tpu/jobs.py,
docs/ROBUSTNESS.md "Preemption & resumable jobs").

Four layers, mirroring the tentpole:

- **artifact format + stage machine** unit tests: checksummed save/load
  round-trips, corruption/tamper/key-mismatch fall back to recompute
  (never trusted), manifest lifecycle across restarts;
- **graceful drain** unit tests on the injectable GracefulDrain (first
  signal -> DrainInterrupt at the next safe point, second signal ->
  hard exit 128+signum, deadline arithmetic, off-main-thread degrade);
- **resume correctness** through the real CLI in-process: stage skips
  on matching fingerprints, recompute on config-hash mismatch, corrupt
  artifacts recomputed cleanly, resumed-vs-uninterrupted bit-identity;
- **process-kill chaos** through REAL subprocesses (testing/faults.py
  ProcessKillPlan / run_job_subprocess): seeded SIGTERM exercises the
  drain (exit 75) and SIGKILL the no-warning preemption; the resumed
  jobs must complete with oracle-parity ranks, skip ingest + the
  composite-key sort (stage records in the resumed run report), and
  the kill placement must be bit-for-bit reproducible.

Plus the exit-code taxonomy regression (pagerank_tpu/exitcodes.py) and
the AsyncRankWriter drain-deadline regression (a failing sink drains to
dead_letter.json inside the deadline; a HANGING sink is abandoned at
it).
"""

import json
import os
import signal
import threading
import time
import warnings

import numpy as np
import pytest

from pagerank_tpu import PageRankConfig, ReferenceCpuEngine, build_graph, jobs
from pagerank_tpu.cli import main as cli_main
from pagerank_tpu.exitcodes import ExitCode, hard_exit_code
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.testing.faults import ProcessKillPlan, run_job_subprocess
from pagerank_tpu.utils.retry import RetryPolicy
from pagerank_tpu.utils.snapshot import AsyncRankWriter, SinkGuard


def read_ranks_tsv(path, n):
    out = np.zeros(n)
    with open(path) as f:
        for line in f:
            k, v = line.split("\t")
            out[int(k)] = float(v)
    return out


# -- artifact format --------------------------------------------------------


def test_artifact_round_trip(tmp_path):
    p = str(tmp_path / "a.npz")
    arrays = {"x": np.arange(6, dtype=np.int32).reshape(2, 3),
              "y": np.ones(4, np.float32)}
    meta = {"stage": "test", "n": 6, "fingerprint": "abc"}
    jobs.save_artifact(p, arrays, meta)
    arrs, m = jobs.load_artifact(p)
    assert m == meta
    np.testing.assert_array_equal(arrs["x"], arrays["x"])
    np.testing.assert_array_equal(arrs["y"], arrays["y"])


def test_artifact_tamper_detected(tmp_path):
    p = str(tmp_path / "a.npz")
    jobs.save_artifact(p, {"x": np.zeros(64, np.float64)}, {"k": 1})
    raw = bytearray(open(p, "rb").read())
    # Flip one payload byte mid-file; zip members are STORED
    # (np.savez without compression), so this lands in array bytes
    # without breaking the container.
    raw[len(raw) // 2] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    with pytest.raises((jobs.ArtifactCorruptError,)):
        jobs.load_artifact(p)


def test_artifact_garbage_and_truncation_detected(tmp_path):
    p = str(tmp_path / "a.npz")
    open(p, "wb").write(b"not a zip at all")
    with pytest.raises(jobs.ArtifactCorruptError):
        jobs.load_artifact(p)
    jobs.save_artifact(p, {"x": np.ones(1024)}, {})
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[: len(raw) // 2])
    with pytest.raises(jobs.ArtifactCorruptError):
        jobs.load_artifact(p)
    with pytest.raises(FileNotFoundError):
        jobs.load_artifact(str(tmp_path / "absent.npz"))


def test_names_round_trip_unicode():
    names = ["http://a/é", "b", "", "漢字"]
    assert jobs.decode_names(jobs.encode_names(names)) == names
    assert jobs.decode_names({}) is None


def test_host_graph_artifact_round_trip():
    rng = np.random.default_rng(0)
    g = build_graph(rng.integers(0, 50, 300), rng.integers(0, 50, 300),
                    n=50)
    arrays, meta = jobs.graph_to_arrays(g)
    g2 = jobs.graph_from_arrays(arrays, meta)
    assert g2.fingerprint() == g.fingerprint()
    np.testing.assert_array_equal(g2.src, g.src)
    np.testing.assert_array_equal(g2.out_degree, g.out_degree)
    # A damaged payload that still loads must fail the fingerprint
    # re-check, not resume against the wrong adjacency.
    bad = dict(arrays)
    bad["dst"] = np.ascontiguousarray(arrays["dst"][::-1])
    with pytest.raises(jobs.ArtifactCorruptError):
        jobs.graph_from_arrays(bad, meta)


def test_config_hashes_key_the_right_fields():
    a = PageRankConfig(num_iters=5)
    assert jobs.graph_config_hash(a) == jobs.graph_config_hash(
        a.replace(num_iters=9))          # solve-only field
    assert jobs.solve_config_hash(a) != jobs.solve_config_hash(
        a.replace(num_iters=9))
    assert jobs.graph_config_hash(a) != jobs.graph_config_hash(
        a.replace(dtype="bfloat16"))     # layout field moves both
    assert jobs.solve_config_hash(a) != jobs.solve_config_hash(
        a.replace(dtype="bfloat16"))
    assert jobs.solve_config_hash(a) != jobs.solve_config_hash(
        a.replace(damping=0.9))


# -- stage machine ----------------------------------------------------------


def test_supervisor_manifest_lifecycle(tmp_path):
    d = str(tmp_path / "job")
    sup = jobs.JobSupervisor(d)
    assert not sup.resumed and sup.manifest["resumes"] == 0
    sup.begin("ingest")
    sup.complete("ingest", fingerprint="fp")
    sup.skip("build")
    # A second supervisor over the same dir is a RESUME.
    sup2 = jobs.JobSupervisor(d)
    assert sup2.resumed and sup2.manifest["resumes"] == 1
    st = sup2.manifest["stages"]
    assert st["ingest"]["status"] == "done" and not st["ingest"]["skipped"]
    assert st["build"]["skipped"] and st["build"]["wall_s"] == 0.0
    sec = sup2.report_section()
    assert sec["resumes"] == 1 and sec["stages"]["build"]["skipped"]


def test_supervisor_survives_garbage_manifest(tmp_path):
    d = str(tmp_path / "job")
    os.makedirs(d)
    with open(os.path.join(d, jobs.MANIFEST_NAME), "w") as f:
        f.write("{torn write")
    with pytest.warns(RuntimeWarning, match="unreadable"):
        sup = jobs.JobSupervisor(d)
    # A torn manifest costs bookkeeping, never correctness: fresh
    # manifest, not-a-resume (artifacts still validate independently).
    assert not sup.resumed and sup.manifest["resumes"] == 0


def test_stage_artifact_key_mismatch_recomputed(tmp_path):
    obs_metrics.get_registry().reset()
    sup = jobs.JobSupervisor(str(tmp_path / "job"))
    sup.save_stage_artifact("solve", {"ranks": np.ones(3)},
                            {"fingerprint": "A", "solve_config": "h1"})
    ok = sup.load_stage_artifact(
        "solve", expect={"fingerprint": "A", "solve_config": "h1"})
    assert ok is not None
    with pytest.warns(RuntimeWarning, match="key mismatch"):
        miss = sup.load_stage_artifact(
            "solve", expect={"fingerprint": "A", "solve_config": "h2"})
    assert miss is None
    snap = obs_metrics.get_registry().snapshot()
    assert snap["counters"]["job.artifacts_rejected"] == 1


def test_stage_artifact_corruption_recomputed(tmp_path):
    sup = jobs.JobSupervisor(str(tmp_path / "job"))
    sup.save_stage_artifact("solve", {"ranks": np.ones(3)}, {"k": 1})
    open(sup.artifact_path("solve"), "wb").write(b"junk")
    with pytest.warns(RuntimeWarning, match="corrupt artifact"):
        assert sup.load_stage_artifact("solve") is None
    assert sup.load_stage_artifact("output") is None  # absent: silent


# -- device-build checkpoint (ops/device_build.py) --------------------------


def test_device_build_checkpoint_round_trip():
    from pagerank_tpu import JaxTpuEngine
    from pagerank_tpu.ops import device_build as db

    rng = np.random.default_rng(7)
    n, e = 257, 2000
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    dg = db.build_ell_device(src, dst, n)
    arrays, meta = db.checkpoint_arrays(dg)
    assert meta["fingerprint"] == dg.fingerprint()
    dg2 = db.restore_device_graph(
        {k: np.asarray(v) for k, v in arrays.items()}, meta)
    assert dg2.fingerprint() == dg.fingerprint()
    np.testing.assert_array_equal(np.asarray(dg2.src), np.asarray(dg.src))
    np.testing.assert_array_equal(np.asarray(dg2.perm), np.asarray(dg.perm))

    # The restored graph solves identically to the original build.
    cfg = PageRankConfig(num_iters=6, num_devices=1)
    r1 = np.asarray(JaxTpuEngine(cfg).build_device(dg).run())
    r2 = np.asarray(JaxTpuEngine(cfg).build_device(dg2).run())
    np.testing.assert_array_equal(r1, r2)

    # build_device donated the planes away: checkpoint must refuse.
    with pytest.raises(ValueError, match="already consumed"):
        db.checkpoint_arrays(dg)

    # Damaged planes that pass the npz layer still fail the on-device
    # fingerprint re-check.
    bad = {k: np.asarray(v).copy() for k, v in arrays.items()}
    bad["perm"] = bad["perm"][::-1].copy()
    with pytest.raises(ValueError, match="fingerprint"):
        db.restore_device_graph(bad, meta)


# -- graceful drain ---------------------------------------------------------


class _FakeSignals:
    """Injectable signal.signal: records handlers, returns the prior."""

    def __init__(self):
        self.handlers = {}

    def __call__(self, signum, handler):
        prev = self.handlers.get(signum, signal.SIG_DFL)
        self.handlers[signum] = handler
        return prev

    def fire(self, signum):
        self.handlers[signum](signum, None)


def test_drain_first_signal_requests_second_hard_exits():
    obs_metrics.get_registry().reset()
    sigs, exits = _FakeSignals(), []
    d = jobs.GracefulDrain(deadline_s=5.0, install=sigs,
                           hard_exit=exits.append)
    with d:
        d.check("solve")  # no request yet: no-op
        sigs.fire(signal.SIGTERM)
        assert d.requested and d.signum == signal.SIGTERM
        with pytest.raises(jobs.DrainInterrupt) as ei:
            d.check("solve")
        assert ei.value.signum == signal.SIGTERM
        assert exits == []
        sigs.fire(signal.SIGTERM)  # the operator means NOW
        assert exits == [int(ExitCode.SIGTERM_HARD)]
    snap = obs_metrics.get_registry().snapshot()
    assert snap["counters"]["job.drain_requests"] == 1
    assert d.finish() >= 0.0


def test_drain_interrupt_is_base_exception():
    """A preemption must never be swallowed by a best-effort
    ``except Exception`` site (the PTL006 discipline for signals)."""
    assert issubclass(jobs.DrainInterrupt, BaseException)
    assert not issubclass(jobs.DrainInterrupt, Exception)


def test_drain_deadline_arithmetic():
    t = {"now": 100.0}
    sigs = _FakeSignals()
    d = jobs.GracefulDrain(deadline_s=10.0, install=sigs,
                           hard_exit=lambda c: None,
                           clock=lambda: t["now"])
    with d:
        assert d.remaining() is None  # no request yet
        sigs.fire(signal.SIGINT)
        t["now"] = 104.0
        assert d.remaining() == pytest.approx(6.0)
        t["now"] = 200.0
        # Floor: bounded flushes still get one attempt.
        assert d.remaining() == pytest.approx(0.5)
        assert d.finish() == pytest.approx(100.0)


def test_drain_restores_prior_handlers_on_exit():
    sigs = _FakeSignals()
    prior = object()
    sigs.handlers[signal.SIGTERM] = prior
    sigs.handlers[signal.SIGINT] = prior
    d = jobs.GracefulDrain(install=sigs, hard_exit=lambda c: None)
    with d:
        assert sigs.handlers[signal.SIGTERM] == d._handler
    assert sigs.handlers[signal.SIGTERM] is prior
    assert sigs.handlers[signal.SIGINT] is prior


def test_drain_degrades_off_main_thread():
    """CPython refuses handlers off the main thread (ValueError):
    embedded library callers keep working, just without drain."""

    def refuse(signum, handler):
        raise ValueError("signal only works in main thread")

    d = jobs.GracefulDrain(install=refuse, hard_exit=lambda c: None)
    with d:
        d.check("solve")  # never raises: no handler ever installed
    assert not d.requested


# -- exit-code taxonomy (pagerank_tpu/exitcodes.py) -------------------------


def test_exit_code_values_are_pinned():
    """The documented taxonomy IS the contract — a renumber breaks
    schedulers that retry on 75 and CI that distinguishes 1/2/3."""
    assert int(ExitCode.OK) == 0
    assert int(ExitCode.FAILURE) == 1
    assert int(ExitCode.USAGE) == 2
    assert int(ExitCode.PREFLIGHT_UNFIT) == 3
    assert int(ExitCode.INTERRUPTED) == 75
    assert int(ExitCode.SIGINT_HARD) == 130 == hard_exit_code(signal.SIGINT)
    assert int(ExitCode.SIGTERM_HARD) == 143 == hard_exit_code(
        signal.SIGTERM)


def test_cli_usage_codes_match_enum(tmp_path):
    d = str(tmp_path / "job")
    rc = cli_main(["--synthetic", "rmat:8", "--job-dir", d,
                   "--ppr-sources", "random:4", "--log-every", "0"])
    assert rc == int(ExitCode.USAGE)
    rc = cli_main(["--synthetic", "rmat:8", "--job-dir", d,
                   "--drain-deadline", "0", "--log-every", "0"])
    assert rc == int(ExitCode.USAGE)


def test_obs_history_codes_match_enum(tmp_path, capsys):
    from pagerank_tpu.obs.__main__ import main as obs_main

    rc = obs_main(["history", "trend", str(tmp_path / "missing.jsonl")])
    capsys.readouterr()
    assert rc == int(ExitCode.USAGE)


# -- AsyncRankWriter drain deadline -----------------------------------------


def test_writer_drain_failing_sink_dead_letters_inside_deadline(tmp_path):
    """The satellite regression: a SIGTERM drain with a FAILING (not
    hanging) sink must still honor SinkGuard dead-letter semantics —
    the flush completes inside the deadline with dead_letter.json
    written, instead of hanging past it or losing the record."""
    obs_metrics.get_registry().reset()
    dead = str(tmp_path / "dead_letter.json")

    def doomed_sink(i, r):
        raise IOError(f"sink down at {i}")

    guard = SinkGuard(
        retry_policy=RetryPolicy(max_attempts=2, base_delay=0.0),
        on_failure="warn_and_drop", dead_letter_path=dead,
    )
    w = AsyncRankWriter(lambda p: p, [doomed_sink], guard=guard)
    for i in range(3):
        w.submit(i, np.ones(2))
    t0 = time.monotonic()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        w.close(timeout=5.0)  # the drain-deadline close (jobs.py)
    assert time.monotonic() - t0 < 5.0
    manifest = json.loads(open(dead).read())
    assert [d["iteration"] for d in manifest["dropped"]] == [0, 1, 2]
    snap = obs_metrics.get_registry().snapshot()
    # It DRAINED — the deadline was never hit.
    assert "sink.drain_timeouts" not in snap["counters"]


def test_writer_drain_hanging_sink_abandoned_at_deadline():
    """A sink wedged PAST the guard's bounded retries (hung NFS, stuck
    socket) must not hold the drain beyond its deadline: the worker is
    abandoned with a warning + counter, and the process can exit."""
    obs_metrics.get_registry().reset()
    release = threading.Event()

    def wedged_sink(i, r):
        release.wait(timeout=30)

    w = AsyncRankWriter(lambda p: p, [wedged_sink])
    w.submit(0, np.ones(2))
    t0 = time.monotonic()
    with pytest.warns(RuntimeWarning, match="drain deadline"):
        w.close(timeout=0.3)
    assert time.monotonic() - t0 < 5.0
    snap = obs_metrics.get_registry().snapshot()
    assert snap["counters"]["sink.drain_timeouts"] == 1
    # Review regression: a repeat close (the __exit__ after a drain
    # close passes NO timeout) must stay a cheap no-op — no TypeError
    # formatting a None timeout, no second drain_timeouts count.
    w.close()
    snap = obs_metrics.get_registry().snapshot()
    assert snap["counters"]["sink.drain_timeouts"] == 1
    release.set()  # let the daemon worker finish


# -- CLI resume correctness (in-process) ------------------------------------


def _job_args(tmp_path, out_name, iters=6, extra=()):
    return ["--synthetic", "rmat:8", "--iters", str(iters),
            "--engine", "cpu", "--job-dir", str(tmp_path / "job"),
            "--out", str(tmp_path / out_name), "--log-every", "0",
            *extra]


def test_resume_skips_all_stages_bit_identical(tmp_path):
    report = str(tmp_path / "rr.json")
    assert cli_main(_job_args(tmp_path, "r1.tsv")) == 0
    assert cli_main(_job_args(
        tmp_path, "r2.tsv", extra=["--run-report", report])) == 0
    assert (open(tmp_path / "r1.tsv").read()
            == open(tmp_path / "r2.tsv").read())
    doc = json.load(open(report))
    jb = doc["job"]
    assert jb["resumes"] == 1 and jb["status"] == "complete"
    assert jb["stages"]["solve"]["skipped"]
    assert jb["stages"]["build"]["skipped"]
    assert doc["metrics"]["counters"]["job.resumes"] == 1


def test_resume_solve_config_mismatch_recomputes_solve_only(tmp_path):
    report = str(tmp_path / "rr.json")
    assert cli_main(_job_args(tmp_path, "r1.tsv", iters=6)) == 0
    # More iterations: the solve artifact's config hash no longer
    # matches — solve recomputes; the graph stages still skip.
    with pytest.warns(RuntimeWarning, match="key mismatch"):
        rc = cli_main(_job_args(tmp_path, "r2.tsv", iters=9,
                                extra=["--run-report", report]))
    assert rc == 0
    jb = json.load(open(report))["job"]
    assert jb["stages"]["build"]["skipped"]
    assert not jb["stages"]["solve"]["skipped"]
    # And the recomputed solve is the real 9-iteration answer.
    clean = str(tmp_path / "clean.tsv")
    assert cli_main(["--synthetic", "rmat:8", "--iters", "9",
                     "--engine", "cpu", "--out", clean,
                     "--log-every", "0"]) == 0
    assert open(tmp_path / "r2.tsv").read() == open(clean).read()


def test_reconfigured_rerun_never_serves_stale_snapshot(tmp_path):
    """Round-3 review regression (live-reproduced): a COMPLETED job
    rerun with a different --damping used to warm-start the old
    config's snapshots (validated only by fingerprint+semantics),
    run ZERO iterations, and emit the old trajectory's ranks as the
    new config's result. Snapshots are now scoped by solve-config
    hash: the reconfigured rerun solves from r0 and matches a fresh
    run byte-for-byte."""
    assert cli_main(_job_args(tmp_path, "r1.tsv")) == 0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rc = cli_main(_job_args(tmp_path, "r2.tsv",
                                extra=["--damping", "0.5"]))
    assert rc == 0
    clean = str(tmp_path / "clean.tsv")
    assert cli_main(["--synthetic", "rmat:8", "--iters", "6",
                     "--engine", "cpu", "--damping", "0.5",
                     "--out", clean, "--log-every", "0"]) == 0
    assert open(tmp_path / "r2.tsv").read() == open(clean).read()
    assert (open(tmp_path / "r1.tsv").read()
            != open(tmp_path / "r2.tsv").read())


def test_writer_drain_healthy_backlog_flushes_not_abandoned():
    """Round-3 review regression: a SLOW-but-working sink with a full
    queue at close(timeout=) must flush everything — the sentinel put
    retries under the deadline instead of being dropped, so the
    drained worker is never falsely 'abandoned'."""
    obs_metrics.get_registry().reset()
    seen = []

    def slow_sink(i, r):
        time.sleep(0.05)
        seen.append(i)

    w = AsyncRankWriter(lambda p: p, [slow_sink], max_pending=2)
    for i in range(4):
        w.submit(i, np.ones(2))
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)  # none expected
        w.close(timeout=10.0)
    assert seen == [0, 1, 2, 3]
    snap = obs_metrics.get_registry().snapshot()
    assert "sink.drain_timeouts" not in snap["counters"]


def test_resume_corrupt_solve_artifact_recomputed(tmp_path):
    assert cli_main(_job_args(tmp_path, "r1.tsv")) == 0
    solve_npz = tmp_path / "job" / "solve.npz"
    raw = bytearray(solve_npz.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    solve_npz.write_bytes(bytes(raw))
    with pytest.warns(RuntimeWarning, match="corrupt|checksum|unreadable"):
        rc = cli_main(_job_args(tmp_path, "r2.tsv"))
    assert rc == 0
    assert (open(tmp_path / "r1.tsv").read()
            == open(tmp_path / "r2.tsv").read())


def test_resume_foreign_graph_key_recomputes(tmp_path):
    """A job dir reused for a DIFFERENT input must not serve the old
    artifacts: the graph key (input spec + layout args) mismatches and
    everything recomputes."""
    assert cli_main(_job_args(tmp_path, "r1.tsv")) == 0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rc = cli_main(["--synthetic", "rmat:9", "--iters", "6",
                       "--engine", "cpu",
                       "--job-dir", str(tmp_path / "job"),
                       "--out", str(tmp_path / "r2.tsv"),
                       "--log-every", "0"])
    assert rc == 0
    clean = str(tmp_path / "clean.tsv")
    assert cli_main(["--synthetic", "rmat:9", "--iters", "6",
                     "--engine", "cpu", "--out", clean,
                     "--log-every", "0"]) == 0
    assert open(tmp_path / "r2.tsv").read() == open(clean).read()


def test_sigterm_during_build_still_commits_build_artifact(tmp_path,
                                                           monkeypatch):
    """Review regression: the drain checkpoint sits AFTER the artifact
    commit — a SIGTERM that lands while the build pipeline is running
    (the kill plan fires at the build-stage transition, before the
    sort) must still persist build.npz, so the resume skips the work
    that had just finished instead of redoing it."""
    job_dir = tmp_path / "job"
    plan = ProcessKillPlan("build", signum=signal.SIGTERM)
    for k, v in plan.to_env().items():
        monkeypatch.setenv(k, v)
    rc = cli_main(["--synthetic", "rmat:8", "--iters", "4",
                   "--device-build", "--job-dir", str(job_dir),
                   "--log-every", "0"])
    assert rc == int(ExitCode.INTERRUPTED)
    assert (job_dir / "build.npz").exists()
    # Round-3 review regression: the drain raised at the POST-commit
    # checkpoint — the manifest must not downgrade the done build
    # record (its artifact is durable); the interrupt point rides
    # interrupted_after instead.
    man = json.loads((job_dir / "job.json").read_text())
    assert man["status"] == "interrupted"
    assert man["stages"]["build"]["status"] == "done"
    assert man["interrupted_after"] == "build"
    monkeypatch.delenv(ProcessKillPlan.ENV)
    report = str(tmp_path / "rr.json")
    rc = cli_main(["--synthetic", "rmat:8", "--iters", "4",
                   "--device-build", "--job-dir", str(job_dir),
                   "--run-report", report, "--log-every", "0"])
    assert rc == 0
    jb = json.load(open(report))["job"]
    assert jb["stages"]["build"]["skipped"]


def test_rewritten_input_file_invalidates_job_key(tmp_path):
    """Review regression: regenerating the input IN PLACE (same path)
    must not let a resumed job serve the old graph's artifacts — the
    graph key carries the file's (size, mtime) stamp."""
    edges = tmp_path / "e.txt"
    edges.write_text("0 1\n1 2\n2 0\n")
    args = ["--input", str(edges), "--iters", "4", "--engine", "cpu",
            "--job-dir", str(tmp_path / "job"), "--log-every", "0"]
    assert cli_main(args + ["--out", str(tmp_path / "r1.tsv")]) == 0
    # New graph at the SAME path (extra vertex chain -> different n).
    edges.write_text("0 1\n1 2\n2 3\n3 4\n4 0\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rc = cli_main(args + ["--out", str(tmp_path / "r2.tsv")])
    assert rc == 0
    clean = str(tmp_path / "clean.tsv")
    assert cli_main(["--input", str(edges), "--iters", "4",
                     "--engine", "cpu", "--out", clean,
                     "--log-every", "0"]) == 0
    assert open(tmp_path / "r2.tsv").read() == open(clean).read()


def test_names_survive_kill_during_device_build(tmp_path, monkeypatch):
    """Review regression: a crawl job killed DURING the device build
    must still have committed names.npz with the raw-edges ingest
    artifact — every later resume writes urls from --out, never the
    integer ids the restored edge arrays alone would give."""
    crawl = tmp_path / "crawl.tsv"
    link = json.dumps({"content": {"links": [
        {"href": "http://b", "type": "a"}]}})
    crawl.write_text(
        f"http://a\t{link}\n"
        f"http://b\t" + json.dumps({"content": {"links": []}}) + "\n")
    job_dir = tmp_path / "job"
    base = ["--input", str(crawl), "--device-build",
            "--job-dir", str(job_dir), "--iters", "3",
            "--log-every", "0"]
    plan = ProcessKillPlan("build", signum=signal.SIGTERM)
    for k, v in plan.to_env().items():
        monkeypatch.setenv(k, v)
    rc = cli_main(base)
    assert rc == int(ExitCode.INTERRUPTED)
    assert (job_dir / "names.npz").exists()
    monkeypatch.delenv(ProcessKillPlan.ENV)
    out = tmp_path / "r.tsv"
    assert cli_main(base + ["--out", str(out)]) == 0
    assert "http://a" in out.read_text()


def test_stages_skipped_gauge_counts_this_run_only(tmp_path):
    """Review regression: a reloaded manifest carries the PRIOR run's
    skipped flags — the gauge must count the current run's skips."""
    obs_metrics.get_registry().reset()
    d = str(tmp_path / "job")
    sup = jobs.JobSupervisor(d)
    sup.skip("ingest")
    sup.skip("build")
    # Second resume: the manifest already says ingest+build skipped.
    obs_metrics.get_registry().reset()
    sup2 = jobs.JobSupervisor(d)
    sup2.skip("ingest")
    snap = obs_metrics.get_registry().snapshot()
    assert snap["gauges"]["job.stages_skipped"] == 1
    sup2.skip("build")
    snap = obs_metrics.get_registry().snapshot()
    assert snap["gauges"]["job.stages_skipped"] == 2


def test_job_key_covers_strict_parse(tmp_path):
    """Review regression: --strict-parse changes the edge SET (lenient
    parses drop malformed crawl entries) — artifacts from the other
    mode must not validate."""
    from pagerank_tpu.cli import _job_graph_key, build_parser

    base = ["--input", str(tmp_path / "c.tsv"), "--job-dir", "j"]
    a = build_parser().parse_args(base)
    b = build_parser().parse_args(base + ["--strict-parse"])
    assert _job_graph_key(a) != _job_graph_key(b)


def test_writer_drain_full_queue_wedged_sink_still_bounded():
    """Review regression: close(timeout=...) with the bounded queue
    FULL and the worker wedged must not block on the sentinel put —
    the drain deadline bounds the whole close, not just the join."""
    release = threading.Event()

    def wedged_sink(i, r):
        release.wait(timeout=30)

    w = AsyncRankWriter(lambda p: p, [wedged_sink], max_pending=2)
    for i in range(3):  # worker takes #0 and wedges; queue holds 2
        w.submit(i, np.ones(2))
    t0 = time.monotonic()
    with pytest.warns(RuntimeWarning, match="drain deadline"):
        w.close(timeout=0.3)
    assert time.monotonic() - t0 < 5.0
    release.set()


def test_resume_file_input_skips_host_parse(tmp_path):
    """File-input ingest artifact: the resumed run restores the BUILT
    host graph (post-dedup/sort) and keeps vertex names for --out."""
    edges = tmp_path / "e.txt"
    edges.write_text("".join(f"{i % 23} {(i * 7) % 23}\n"
                             for i in range(100)))
    args = ["--input", str(edges), "--iters", "5", "--engine", "cpu",
            "--job-dir", str(tmp_path / "job"), "--log-every", "0"]
    report = str(tmp_path / "rr.json")
    assert cli_main(args + ["--out", str(tmp_path / "r1.tsv")]) == 0
    assert cli_main(args + ["--out", str(tmp_path / "r2.tsv"),
                            "--run-report", report]) == 0
    assert (open(tmp_path / "r1.tsv").read()
            == open(tmp_path / "r2.tsv").read())
    jb = json.load(open(report))["job"]
    assert jb["stages"]["ingest"]["skipped"]


# -- process-kill chaos (real subprocesses) ---------------------------------

pytestmark_chaos = pytest.mark.usefixtures()


def _chaos_argv(job_dir, out, iters=8, report=None, device_build=False):
    argv = ["--synthetic", "rmat:8", "--iters", str(iters),
            "--job-dir", str(job_dir), "--out", str(out),
            "--log-every", "0"]
    if device_build:
        argv += ["--device-build"]
    if report:
        argv += ["--run-report", str(report)]
    return argv


def _oracle_ranks(scale=8, iters=8):
    from pagerank_tpu.utils.synth import rmat_edges

    src, dst = rmat_edges(scale, edge_factor=16, seed=0)
    g = build_graph(src, dst, n=1 << scale)
    cfg = PageRankConfig(num_iters=iters, dtype="float64",
                         accum_dtype="float64")
    return ReferenceCpuEngine(cfg).build(g).run(), g.n


@pytest.fixture(scope="module")
def sigterm_chaos(tmp_path_factory):
    """One seeded SIGTERM chaos pair (kill at solve iter 2 -> resume),
    run twice in separate job dirs for the reproducibility assert."""
    root = tmp_path_factory.mktemp("sigterm_chaos")
    runs = {}
    for tag in ("a", "b"):
        job = root / f"job_{tag}"
        out = root / f"ranks_{tag}.tsv"
        log = root / f"kill_{tag}.log"
        report = root / f"report_{tag}.json"
        plan = ProcessKillPlan("solve", iteration=2,
                               signum=signal.SIGTERM, seed=7)
        kill_report = root / f"kill_report_{tag}.json"
        killed = run_job_subprocess(
            _chaos_argv(job, out) + ["--run-report", str(kill_report)],
            kill=plan, kill_log=str(log), timeout=300.0)
        manifest_after_kill = json.loads((job / "job.json").read_text())
        resumed = run_job_subprocess(
            _chaos_argv(job, out, report=report), timeout=300.0)
        runs[tag] = dict(job=job, out=out, log=log, report=report,
                         kill_report=kill_report, killed=killed,
                         resumed=resumed,
                         manifest_after_kill=manifest_after_kill)
    return runs


def test_sigterm_drain_exits_interrupted(sigterm_chaos):
    r = sigterm_chaos["a"]
    assert r["killed"].returncode == int(ExitCode.INTERRUPTED), \
        r["killed"].stderr[-2000:]
    assert "draining" in r["killed"].stderr
    assert "interrupted by SIGTERM" in r["killed"].stderr
    # The drain left a resumable dir: manifest marked interrupted
    # (read between the kill and the resume — the resume completes it).
    man = r["manifest_after_kill"]
    assert man["status"] == "interrupted"
    assert man["stages"]["solve"]["status"] == "interrupted"
    # ... and the drain exported an interrupted-MARKED run report from
    # whatever state existed (the flight-recorder half of the drain).
    doc = json.loads(r["kill_report"].read_text())
    assert doc["interrupted"] is True
    assert doc["interrupt_signal"] == signal.SIGTERM
    assert doc["job"]["status"] == "interrupted"


def test_sigterm_resume_completes_with_oracle_parity(sigterm_chaos):
    r = sigterm_chaos["a"]
    assert r["resumed"].returncode == 0, r["resumed"].stderr[-2000:]
    expected, n = _oracle_ranks()
    got = read_ranks_tsv(r["out"], n)
    l1 = float(np.abs(got - expected).sum() / np.abs(expected).sum())
    assert l1 < 1e-4  # f32 run vs f64 oracle
    doc = json.loads(r["report"].read_text())
    assert doc["job"]["resumes"] == 1
    assert doc["job"]["status"] == "complete"
    # Bounded recomputed work: the resumed solve warm-started from the
    # drain snapshot instead of restarting at r0.
    assert "resumed from iteration" in r["resumed"].stderr


def test_sigterm_resume_bit_identical_to_uninterrupted(tmp_path,
                                                       sigterm_chaos):
    """The acceptance bit-identity: interrupted-at-iter-2 + resumed
    == one uninterrupted run, byte-for-byte at f32. The clean run is a
    subprocess too, so both sides share the child environment (the
    in-process pytest interpreter has x64 enabled, children don't)."""
    clean = tmp_path / "clean.tsv"
    cp = run_job_subprocess(
        ["--synthetic", "rmat:8", "--iters", "8", "--out", str(clean),
         "--log-every", "0"], timeout=300.0)
    assert cp.returncode == 0, cp.stderr[-2000:]
    assert sigterm_chaos["a"]["out"].read_text() == clean.read_text()


def test_sigterm_chaos_bit_for_bit_reproducible(sigterm_chaos):
    a, b = sigterm_chaos["a"], sigterm_chaos["b"]
    assert a["log"].read_text() == b["log"].read_text() != ""
    assert a["log"].read_text() == "solve,SIGTERM,2\n"
    assert a["out"].read_text() == b["out"].read_text()


@pytest.fixture(scope="module")
def sigkill_chaos(tmp_path_factory):
    """SIGKILL (no-warning preemption) mid-solve on a --device-build
    job: the build artifact committed BEFORE the solve must carry the
    resume past ingest AND the composite-key sort."""
    root = tmp_path_factory.mktemp("sigkill_chaos")
    job, out = root / "job", root / "ranks.tsv"
    report = root / "report.json"
    plan = ProcessKillPlan("solve", iteration=1, signum=signal.SIGKILL)
    killed = run_job_subprocess(
        _chaos_argv(job, out, device_build=True), kill=plan,
        timeout=300.0)
    resumed = run_job_subprocess(
        _chaos_argv(job, out, device_build=True, report=report),
        timeout=300.0)
    clean_out = root / "clean.tsv"
    clean = run_job_subprocess(
        ["--synthetic", "rmat:8", "--iters", "8", "--device-build",
         "--out", str(clean_out), "--log-every", "0"], timeout=300.0)
    return dict(job=job, out=out, report=report, killed=killed,
                resumed=resumed, clean=clean, clean_out=clean_out)


def test_sigkill_leaves_shell_convention_code(sigkill_chaos):
    assert sigkill_chaos["killed"].returncode == -signal.SIGKILL
    assert (sigkill_chaos["job"] / "build.npz").exists()


def test_sigkill_resume_skips_ingest_and_sort(sigkill_chaos):
    """The acceptance criterion: a SIGKILL'd job resumes without
    re-running ingest or the composite-key sort — the resumed run
    report's stage records prove it (skipped=True, wall_s=0)."""
    r = sigkill_chaos
    assert r["resumed"].returncode == 0, r["resumed"].stderr[-2000:]
    doc = json.loads(r["report"].read_text())
    jb = doc["job"]
    assert jb["resumes"] == 1 and jb["status"] == "complete"
    assert jb["stages"]["ingest"]["skipped"]
    assert jb["stages"]["build"]["skipped"]
    assert jb["stages"]["build"]["wall_s"] == 0.0
    # The sort never ran: no job/build span in the resumed trace, only
    # the cheap artifact restore (spans are keyed by name in the
    # report's tracer summary).
    spans = doc.get("spans") or {}
    assert "job/build" not in spans
    assert "job/build_restore" in spans
    assert not jb["stages"]["solve"]["skipped"]  # solve really re-ran

    # The resume solved against the RESTORED packed planes (the killed
    # child's sort output); an uninterrupted clean job regenerates and
    # re-sorts — byte-identical final ranks prove the restore is exact.
    assert r["clean"].returncode == 0, r["clean"].stderr[-2000:]
    assert r["out"].read_text() == r["clean_out"].read_text()


def test_kill_plan_env_round_trip():
    plan = ProcessKillPlan("build", iteration=None,
                           signum=signal.SIGKILL, seed=3)
    env = plan.to_env()
    back = ProcessKillPlan.from_env(env)
    assert (back.stage, back.iteration, back.signum, back.seed) == \
        ("build", None, signal.SIGKILL, 3)
    assert ProcessKillPlan.from_env({}) is None
    with pytest.raises(ValueError, match="unknown signal"):
        ProcessKillPlan.from_env(
            {ProcessKillPlan.ENV: "stage=solve,signal=BOGUS"})


def test_kill_plan_is_one_shot_and_stage_scoped(monkeypatch):
    fired = []
    plan = ProcessKillPlan("solve", iteration=3, signum=signal.SIGTERM)
    # Patch the delivery so the test process survives.
    monkeypatch.setattr(os, "kill", lambda pid, sig: fired.append(sig))
    plan.check("ingest", None)
    plan.check("solve", 2)
    assert fired == [] and not plan.fired
    plan.check("solve", 3)
    assert fired == [signal.SIGTERM] and plan.fired
    plan.check("solve", 3)  # one-shot
    assert fired == [signal.SIGTERM]
    assert plan.log == [("solve", "SIGTERM", 3)]
