"""Source-striped ELL packing (ops/ell.py:ell_pack_striped) — the
large-graph layout that keeps each per-stripe gather table inside the
fast XLA regime (engines/jax_engine.py:_stripe_max)."""

import numpy as np
import pytest

from pagerank_tpu import JaxTpuEngine, PageRankConfig, ReferenceCpuEngine, build_graph
from pagerank_tpu.ops import ell as ell_lib


def _graph(rng, n=1000, e=8000):
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


def test_striped_pack_covers_all_edges_once():
    rng = np.random.default_rng(0)
    g = _graph(rng)
    pack = ell_lib.ell_pack_striped(g, stripe_size=256)
    assert pack.n_stripes == -(-pack.n_padded // 256)
    # Every real edge appears exactly once across stripes.
    total = sum(int((w != 0).sum()) for w in pack.weight)
    assert total == g.num_edges == pack.num_real_edges
    # Stripe-local ids are in range, and slot weights match 1/out_degree.
    inv = np.zeros(g.n)
    nz = g.out_degree > 0
    inv[nz] = 1.0 / g.out_degree[nz]
    for s, (src, w, rb) in enumerate(zip(pack.src, pack.weight, pack.row_block)):
        assert src.min(initial=0) >= 0 and src.max(initial=0) < 256
        mask = w != 0
        glob = src[mask] + s * 256  # relabeled source ids
        np.testing.assert_allclose(w[mask], inv[pack.perm[glob]])
        assert np.all(np.diff(rb) >= 0)  # ascending block ids


def test_striped_spmv_matches_unstriped():
    rng = np.random.default_rng(1)
    g = _graph(rng)
    single = ell_lib.ell_pack(g)
    striped = ell_lib.ell_pack_striped(g, stripe_size=128)
    z = rng.random(g.n)
    want = ell_lib.ell_spmv_reference(single, z)
    got = np.zeros(striped.n_padded)
    for s, (src, w, rb) in enumerate(
        zip(striped.src, striped.weight, striped.row_block)
    ):
        lo = s * striped.stripe_size
        v = np.where(w != 0, z[np.clip(src + lo, 0, g.n - 1)] * w, 0.0)
        y2 = np.zeros((striped.num_blocks, 128))
        np.add.at(y2, rb, v)
        got += y2.reshape(-1)
    np.testing.assert_allclose(got[: g.n], want, rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("ndev", [1, 2])
@pytest.mark.parametrize("accum", ["float32", "float64"])
def test_striped_engine_matches_unstriped(monkeypatch, ndev, accum):
    rng = np.random.default_rng(2)
    g = _graph(rng)
    cfg = PageRankConfig(
        num_iters=10, dtype="float32", accum_dtype=accum,
        wide_accum="pair", num_devices=ndev,
    )
    r_plain = JaxTpuEngine(cfg).build(g).run_fast()
    monkeypatch.setattr(JaxTpuEngine, "_stripe_max", lambda self: 256)
    monkeypatch.setattr(JaxTpuEngine, "_stripe_target", lambda self: 256)
    eng = JaxTpuEngine(cfg).build(g)
    assert len(eng._src) == -(-eng._n_state // 256)
    r_striped = eng.run_fast()
    # Same products, same per-row reduction order within a stripe; only
    # the cross-stripe add order differs.
    np.testing.assert_allclose(r_striped, r_plain, rtol=1e-6, atol=1e-7)
    r_cpu = ReferenceCpuEngine(cfg).build(g).run()
    assert np.abs(r_striped - r_cpu).sum() / np.abs(r_cpu).sum() < 1e-5


def test_striped_engine_f64_matches_oracle(monkeypatch):
    rng = np.random.default_rng(3)
    g = _graph(rng)
    monkeypatch.setattr(JaxTpuEngine, "_stripe_max", lambda self: 384)
    monkeypatch.setattr(JaxTpuEngine, "_stripe_target", lambda self: 384)
    cfg = PageRankConfig(num_iters=12, dtype="float64", accum_dtype="float64")
    r = JaxTpuEngine(cfg).build(g).run_fast()
    r_cpu = ReferenceCpuEngine(cfg).build(g).run()
    np.testing.assert_allclose(r, r_cpu, rtol=0, atol=1e-11)


def test_bad_stripe_size_rejected():
    rng = np.random.default_rng(4)
    g = _graph(rng, n=100, e=200)
    with pytest.raises(ValueError):
        ell_lib.ell_pack_striped(g, stripe_size=100)  # not multiple of 128
    with pytest.raises(ValueError):
        ell_lib.ell_pack_striped(g, stripe_size=0)


@pytest.mark.parametrize("ndev", [1, 2])
@pytest.mark.parametrize("accum", ["float32", "float64"])
def test_scan_stripes_fallback_matches_unstriped(monkeypatch, ndev, accum):
    """Past SCAN_STRIPE_UNITS every run form steps the multi-dispatch
    machinery (one exact-shape executable per stripe; run_fused and
    run_fused_chunked delegate/pipeline through it — the in-program
    scan fallback was removed in r3). All must produce the same ranks
    as the unstriped engine (and, transitively through
    test_striped_engine_matches_unstriped, the unrolled striped form)."""
    rng = np.random.default_rng(5)
    g = _graph(rng)
    cfg = PageRankConfig(
        num_iters=10, dtype="float32", accum_dtype=accum,
        wide_accum="pair", num_devices=ndev,
    )
    r_plain = JaxTpuEngine(cfg).build(g).run_fast()
    monkeypatch.setattr(JaxTpuEngine, "_stripe_max", lambda self: 256)
    monkeypatch.setattr(JaxTpuEngine, "_stripe_target", lambda self: 256)
    monkeypatch.setattr(JaxTpuEngine, "SCAN_STRIPE_UNITS", 0)  # force it
    eng = JaxTpuEngine(cfg).build(g)
    S = -(-eng._n_state // 256)
    assert len(eng._src) == S
    assert eng._ms_stripe is not None  # multi-dispatch stepwise engaged
    assert len(eng._ms_stripe_fns) == S  # one executable per stripe shape
    r_md = eng.run_fast()
    np.testing.assert_allclose(r_md, r_plain, rtol=1e-6, atol=1e-7)
    # run_fused delegates to the one-chunk multi-dispatch form here
    # (full per-iteration traces, same contract).
    eng2 = JaxTpuEngine(cfg).build(g)
    r_fused = eng2.run_fused()
    np.testing.assert_allclose(r_fused, r_plain, rtol=1e-6, atol=1e-7)
    assert len(np.asarray(eng2.last_run_metrics["l1_delta"])) == 10
    # And fused-chunked, which steps via the multi-dispatch path.
    eng3 = JaxTpuEngine(cfg).build(g)
    r_ck = eng3.run_fused_chunked(every=3)
    np.testing.assert_allclose(r_ck, r_plain, rtol=1e-6, atol=1e-7)


def test_fused_tol_routes_to_chunked_on_ms_layouts(monkeypatch):
    """On very-many-stripe layouts run_fused_tol must take the fast
    multi-dispatch chunked form, not the scan-over-stripes while_loop
    (which loses XLA's fast gather — PERF_NOTES "Scan bodies defeat the
    fast gather"); VERDICT r2 #4. Stopping iteration must match the
    host-checked stepwise tol run exactly (per-iteration check)."""
    rng = np.random.default_rng(7)
    g = _graph(rng)
    tol = 0.05
    cfg = PageRankConfig(num_iters=100, dtype="float32",
                         accum_dtype="float64", tol=tol)
    ref = JaxTpuEngine(cfg).build(g)
    ref.run(on_iteration=lambda i, info: None)
    stop_iter = ref.iteration
    assert 0 < stop_iter < 100  # tol actually fired mid-run

    monkeypatch.setattr(JaxTpuEngine, "_stripe_max", lambda self: 256)
    monkeypatch.setattr(JaxTpuEngine, "_stripe_target", lambda self: 256)
    monkeypatch.setattr(JaxTpuEngine, "SCAN_STRIPE_UNITS", 0)
    eng = JaxTpuEngine(cfg).build(g)
    assert eng._ms_stripe is not None
    # prepare_fused(tol=...) must warm the multi-dispatch executables
    # (what the delegated path runs), NOT compile the while_loop form
    # the delegation never executes.
    assert eng.prepare_fused(tol=tol) == 100
    assert not any(isinstance(k, tuple) and k[0] == "tol"
                   for k in eng._fused_cache)
    assert eng.iteration == 0  # warm-up step did not advance state
    called = {}
    orig = JaxTpuEngine.run_fused_chunked

    def spy(self, *a, **kw):
        called["kw"] = kw
        return orig(self, *a, **kw)

    monkeypatch.setattr(JaxTpuEngine, "run_fused_chunked", spy)
    r = eng.run_fused_tol(tol)
    assert called["kw"].get("tol") == tol  # routing pinned
    assert eng.iteration == stop_iter  # identical stopping point
    np.testing.assert_allclose(r, ref.ranks(), rtol=1e-6, atol=1e-7)
    # Full per-iteration traces survive (strictly more than the
    # while_loop form's final-only contract).
    assert len(np.asarray(eng.last_run_metrics["l1_delta"])) == stop_iter


def test_occupancy_span_rule():
    """Sparse layouts widen the stripe span while a typical cell at
    most fills one row, capped by the 2^17-gather-row bound at the
    dtype's widest gather: pair doubles once (measured 1.52e8 ->
    1.98e8 at R-MAT 26 ef 8), f32 doubles twice (2.71e8 -> 3.95e8).
    Dense, unknown-edge-count, and unstriped layouts keep the span
    (measured regressions otherwise: PERF_NOTES "Occupancy-aware
    stripes")."""
    smax = 4194304
    n26, e26 = 1 << 26, 8 << 26  # ef 8: 64 edges/cell at smax
    # pair: gather bound 64 << 17 = 8.4M -> one doubling
    assert JaxTpuEngine.occupancy_span(smax, n26, e26, True) == 2 * smax
    # f32: bound 128 << 17 = 16.8M -> two doublings
    assert JaxTpuEngine.occupancy_span(smax, n26, e26, False, 4) == 4 * smax
    # native f64 rows (z_item 8): 64-lane cap -> one doubling
    assert JaxTpuEngine.occupancy_span(smax, n26, e26, False, 8) == 2 * smax
    n25, e25 = 1 << 25, 16 << 25  # ef 16: 253 edges/cell -> keep
    assert JaxTpuEngine.occupancy_span(smax, n25, e25, True) == smax
    assert JaxTpuEngine.occupancy_span(smax, n25, e25, False, 4) == smax
    assert JaxTpuEngine.occupancy_span(smax, n26, None, True) == smax
    assert JaxTpuEngine.occupancy_span(n26, n26, e26, True) == n26
    # widening never exceeds the vertex space
    assert JaxTpuEngine.occupancy_span(smax, 6 * smax // 4, 10, True) \
        == 6 * smax // 4
