"""Partitioned-rank (vertex-sharded) execution mode (VERDICT r3 #1).

The reference's `ranks` RDD is hash-partitioned across executors
(Sparky.java:165-170); the replicated mode instead keeps every
per-vertex vector whole on every chip. `config.vertex_sharded` shards
the rank vector, masks, and 1/out-degree over the mesh. Equality
contract vs the replicated mode, pinned here on the 8-fake-device CPU
mesh:

- The contribution merge is BIT-EXACT (psum_scatter slices agree with
  psum bitwise): the first step from the integer-exact r0 produces
  bit-equal ranks (test_first_step_bitequal).
- f32-STORAGE configs (including the pair-f64-accum large-graph
  layout) stay bit-equal over full runs at every dispatch form: the
  f32 round absorbs the one place the modes legitimately differ — the
  dangling-mass/L1 scalar reductions regroup (per-shard partial + psum
  vs one full-vector reduce), a <= 1-ulp f64 effect per iteration.
- f64-storage runs carry that ulp into the ranks: measured max 4 nulp
  after 50 iterations (no amplification); asserted <= 8 nulp here.
"""

import numpy as np
import pytest

from pagerank_tpu import JaxTpuEngine, PageRankConfig, build_graph
from pagerank_tpu.utils.synth import rmat_edges


@pytest.fixture(scope="module")
def graph():
    src, dst = rmat_edges(10, edge_factor=8, seed=1)
    return build_graph(src, dst, n=1 << 10)


class _TinyStripes(JaxTpuEngine):
    """Forces the striped layout at toy scale (same pattern as
    __graft_entry__.dryrun_multichip)."""

    def _stripe_max(self):
        return 256

    def _stripe_target(self):
        return 256


class _TinyScan(_TinyStripes):
    SCAN_STRIPE_UNITS = 0  # forces the multi-dispatch machinery


CFG64 = PageRankConfig(num_iters=8, dtype="float64", accum_dtype="float64")


@pytest.mark.parametrize("ndev", [1, 2, 8])
def test_vertex_sharded_matches_replicated_f64(graph, ndev):
    cfg = CFG64.replace(num_devices=ndev)
    r_rep = JaxTpuEngine(cfg).build(graph).run()
    r_vs = JaxTpuEngine(cfg.replace(vertex_sharded=True)).build(graph).run()
    if ndev == 1:
        np.testing.assert_array_equal(r_vs, r_rep)  # no regrouping at all
    else:
        # The mass/L1 scalar reductions regroup across shards: <= 1 ulp
        # per iteration, measured max 4 nulp after 50 (module docstring).
        np.testing.assert_array_almost_equal_nulp(r_vs, r_rep, nulp=8)


@pytest.mark.parametrize("ndev", [2, 8])
def test_first_step_bitequal(graph, ndev):
    """From the integer-exact r0, one step is BIT-equal: pins that the
    psum_scatter contribution merge agrees with psum bitwise (the only
    inexact divergence between the modes is the mass/L1 scalar
    regrouping, which is exact at iteration 0 where r0 is all-ones)."""
    cfg = CFG64.replace(num_devices=ndev, num_iters=1)
    r_rep = JaxTpuEngine(cfg).build(graph).run()
    r_vs = JaxTpuEngine(cfg.replace(vertex_sharded=True)).build(graph).run()
    np.testing.assert_array_equal(r_vs, r_rep)


def test_vertex_sharded_state_is_partitioned(graph):
    from jax.sharding import PartitionSpec as P

    eng = JaxTpuEngine(
        CFG64.replace(num_devices=8, vertex_sharded=True)
    ).build(graph)
    spec = P(eng.config.mesh_axis)
    for arr in (eng._r, eng._inv_out, eng._dangling, eng._zero_in,
                eng._valid):
        assert arr.sharding.spec == spec, arr.sharding
        # one shard per device, each 1/8 of the padded state
        assert arr.addressable_shards[0].data.shape[0] == arr.shape[0] // 8
    rep_eng = JaxTpuEngine(CFG64.replace(num_devices=8)).build(graph)
    assert rep_eng._r.sharding.spec == P()


def test_vertex_sharded_striped_pair_bitequal(graph):
    cfg = PageRankConfig(
        num_iters=4, dtype="float32", accum_dtype="float64",
        wide_accum="pair", num_devices=8,
    )
    rep = _TinyStripes(cfg).build(graph)
    assert len(rep._src) > 1  # really striped
    r_rep = rep.run_fast()
    vs = _TinyStripes(cfg.replace(vertex_sharded=True)).build(graph)
    assert vs._ms_stripe is None  # unrolled single-program form
    np.testing.assert_array_equal(vs.run_fast(), r_rep)


def test_vertex_sharded_multi_dispatch_bitequal(graph):
    cfg = PageRankConfig(
        num_iters=4, dtype="float32", accum_dtype="float64",
        wide_accum="pair", num_devices=8,
    )
    r_rep = _TinyStripes(cfg).build(graph).run_fast()
    ms = _TinyScan(cfg.replace(vertex_sharded=True)).build(graph)
    assert ms._ms_stripe is not None  # multi-dispatch engaged
    np.testing.assert_array_equal(ms.run_fast(), r_rep)


def test_vertex_sharded_fused_forms_bitequal(graph):
    cfg = PageRankConfig(
        num_iters=4, dtype="float32", accum_dtype="float64",
        wide_accum="pair", num_devices=8, vertex_sharded=True,
    )
    r_step = _TinyStripes(cfg).build(graph).run_fast()
    r_fused = _TinyStripes(cfg).build(graph).run_fused()
    np.testing.assert_array_equal(r_fused, r_step)
    tol_eng = _TinyStripes(cfg.replace(tol=1e-30)).build(graph)
    np.testing.assert_array_equal(tol_eng.run_fused_tol(), r_step)
    chunked = _TinyScan(cfg).build(graph)
    np.testing.assert_array_equal(
        chunked.run_fused_chunked(every=2), r_step
    )
    # traces survive with the right lengths
    assert chunked.last_run_metrics["l1_delta"].shape == (4,)


def test_vertex_sharded_set_ranks_roundtrip(graph):
    eng = JaxTpuEngine(
        CFG64.replace(num_devices=8, vertex_sharded=True)
    ).build(graph)
    r = eng.run()
    eng.set_ranks(r, iteration=8)
    assert eng.iteration == 8
    np.testing.assert_array_equal(eng.ranks(), r)
    # and stepping on from restored state matches an uninterrupted run
    eng2 = JaxTpuEngine(
        CFG64.replace(num_iters=12, num_devices=8, vertex_sharded=True)
    ).build(graph)
    r12 = eng2.run()
    eng.config = eng.config.replace(num_iters=12)
    np.testing.assert_array_equal(eng.run(), r12)


def test_vertex_sharded_device_build_bitequal(graph):
    import jax

    from pagerank_tpu.ops import device_build as db

    src_d, dst_d = db.rmat_edges_device(8, seed=2)
    src_h = np.asarray(jax.device_get(src_d))
    dst_h = np.asarray(jax.device_get(dst_d))
    dg = db.build_ell_device(
        src_d, dst_d, n=1 << 8, group=4, stripe_size=128, with_weights=False
    )
    cfg = PageRankConfig(num_iters=3, num_devices=8, vertex_sharded=True)
    r_dev = JaxTpuEngine(cfg).build_device(dg).run_fast()
    host = JaxTpuEngine(cfg.replace(vertex_sharded=False)).build(
        build_graph(src_h, dst_h, n=1 << 8)
    )
    np.testing.assert_allclose(r_dev, host.run_fast(), rtol=1e-6, atol=1e-7)


def test_vertex_sharded_rejects_non_ell_kernels():
    with pytest.raises(ValueError, match="vertex_sharded"):
        PageRankConfig(vertex_sharded=True, kernel="coo").validate()
    with pytest.raises(ValueError, match="vertex_sharded"):
        PageRankConfig(vertex_sharded=True, kernel="pallas").validate()
    with pytest.raises(ValueError, match="vertex_sharded"):
        JaxTpuEngine(
            PageRankConfig(vertex_sharded=True, kernel="coo")
        ).build(build_graph(np.array([0]), np.array([1]), n=2))


def test_vertex_sharded_cli_smoke(tmp_path, capsys):
    from pagerank_tpu.cli import main

    rng = np.random.default_rng(3)
    p = str(tmp_path / "edges.txt")
    with open(p, "w") as f:
        for s, d in zip(rng.integers(0, 40, 300), rng.integers(0, 40, 300)):
            f.write(f"{s} {d}\n")
    out_vs = str(tmp_path / "vs.tsv")
    out_rep = str(tmp_path / "rep.tsv")
    base = ["--input", p, "--iters", "5", "--log-every", "0",
            "--dtype", "float64"]
    assert main(base + ["--vertex-sharded", "--out", out_vs]) == 0
    assert main(base + ["--out", out_rep]) == 0
    ranks_vs = [float(l.split("\t")[1]) for l in open(out_vs)]
    ranks_rep = [float(l.split("\t")[1]) for l in open(out_rep)]
    np.testing.assert_allclose(ranks_vs, ranks_rep, rtol=1e-13)


# -- bounded-transient (dst-partitioned / owner-computes) mode ----------
# config.vs_bounded (VERDICT r4 #1): dst blocks dealt across device
# ranges, each chip owns its own dst rows, the contribution merge
# disappears, z is broadcast per stripe. Numerics: a block's rows are
# summed on ONE chip instead of split+psum'd, so results agree to
# accumulation-dtype rounding (bit-equal at ndev=1, where the mode
# degenerates to the same row order).

VSB64 = CFG64.replace(vertex_sharded=True, vs_bounded=True)


@pytest.mark.parametrize("ndev", [1, 2, 8])
def test_vs_bounded_matches_replicated_f64(graph, ndev):
    r_rep = JaxTpuEngine(CFG64.replace(num_devices=ndev)).build(graph).run()
    r_b = JaxTpuEngine(VSB64.replace(num_devices=ndev)).build(graph).run()
    if ndev == 1:
        np.testing.assert_array_equal(r_b, r_rep)
    else:
        err = np.abs(r_b - r_rep).sum() / np.abs(r_rep).sum()
        assert err < 1e-13, err


def test_vs_bounded_state_and_rows_partitioned(graph):
    """Persistent state sharded AND every device's slot rows target only
    its own dst-block range (stage b: owner-computes, no merge)."""
    from jax.sharding import PartitionSpec as P

    eng = JaxTpuEngine(VSB64.replace(num_devices=8)).build(graph)
    spec = P(eng.config.mesh_axis)
    for arr in (eng._r, eng._inv_out, eng._dangling, eng._zero_in,
                eng._valid):
        assert arr.sharding.spec == spec
    ndev = 8
    nbd = eng._n_state // 128 // ndev
    ids_args = eng._contrib_args[2::3]  # (src, rb, ids) per stripe
    assert len(ids_args) >= 1
    for ids in ids_args:
        ids = np.asarray(ids)  # [ndev, Ps] LOCAL block ids
        assert ids.shape[0] == ndev
        assert ids.min() >= 0
        # real ids < nbd; pads live in the trash band [nbd, nbd+Ps)
        assert ids.max() < 2 * nbd
        assert np.all(np.diff(ids, axis=1) > 0)  # sorted AND unique


def test_vs_bounded_striped_unrolled_and_multi_dispatch(graph):
    cfg = PageRankConfig(
        num_iters=4, dtype="float32", accum_dtype="float64",
        wide_accum="pair", num_devices=8,
    )
    r_rep = _TinyStripes(cfg).build(graph).run_fast()
    # Below SCAN_STRIPE_UNITS: ONE fused program (the measured-fast
    # form, like the replicated mode).
    eng = _TinyStripes(
        cfg.replace(vertex_sharded=True, vs_bounded=True)
    ).build(graph)
    assert eng._ms_stripe is None
    assert len(eng._src) > 1  # really striped
    r_b = eng.run_fast()
    err = (np.abs(np.float64(r_b) - np.float64(r_rep)).sum()
           / np.abs(np.float64(r_rep)).sum())
    assert err < 1e-6, err
    # Past the threshold: z-broadcast + gather dispatches per stripe.
    ms = _TinyScan(
        cfg.replace(vertex_sharded=True, vs_bounded=True)
    ).build(graph)
    assert ms._ms_stripe is not None
    np.testing.assert_array_equal(ms.run_fast(), r_b)


def test_vs_bounded_fused_forms_match_step(graph):
    cfg = PageRankConfig(
        num_iters=4, dtype="float32", accum_dtype="float64",
        wide_accum="pair", num_devices=8, vertex_sharded=True,
        vs_bounded=True,
    )
    r_step = _TinyStripes(cfg).build(graph).run_fast()
    np.testing.assert_array_equal(
        _TinyStripes(cfg).build(graph).run_fused(), r_step
    )
    tol_eng = _TinyStripes(cfg.replace(tol=1e-30)).build(graph)
    np.testing.assert_array_equal(tol_eng.run_fused_tol(), r_step)
    chunked = _TinyStripes(cfg).build(graph)
    np.testing.assert_array_equal(
        chunked.run_fused_chunked(every=2), r_step
    )
    assert chunked.last_run_metrics["l1_delta"].shape == (4,)


def test_vs_bounded_matches_oracle(graph):
    """The accuracy gate class: bounded mode vs the f64 CPU oracle."""
    from pagerank_tpu import ReferenceCpuEngine

    cfg = VSB64.replace(num_devices=8, num_iters=20)
    r_b = JaxTpuEngine(cfg).build(graph).run()
    r_cpu = ReferenceCpuEngine(
        CFG64.replace(num_iters=20)
    ).build(graph).run()
    err = np.abs(r_b - r_cpu).sum() / np.abs(r_cpu).sum()
    assert err < 1e-12, err


def test_vs_bounded_snapshot_resume(tmp_path, graph):
    from pagerank_tpu.utils.snapshot import Snapshotter, resume_engine

    cfg = VSB64.replace(num_devices=8)
    full = JaxTpuEngine(cfg).build(graph).run()
    snap = Snapshotter(str(tmp_path), graph.fingerprint(), cfg.semantics)
    half = JaxTpuEngine(cfg.replace(num_iters=4)).build(graph)
    snap.save(4, half.run())
    resumed = JaxTpuEngine(cfg).build(graph)
    assert resume_engine(resumed, snap) == 4
    np.testing.assert_array_equal(resumed.run(), full)


def test_vs_bounded_validation_and_device_build(graph):
    with pytest.raises(ValueError, match="vs_bounded"):
        PageRankConfig(vs_bounded=True).validate()
    import jax

    from pagerank_tpu.ops import device_build as db

    src_d, dst_d = db.rmat_edges_device(8, seed=2)
    dg = db.build_ell_device(src_d, dst_d, n=1 << 8)
    with pytest.raises(ValueError, match="host-built"):
        JaxTpuEngine(
            PageRankConfig(num_devices=8, vertex_sharded=True,
                           vs_bounded=True)
        ).build_device(dg)


def test_vs_bounded_cli_smoke(tmp_path):
    from pagerank_tpu.cli import main

    rng = np.random.default_rng(3)
    p = str(tmp_path / "edges.txt")
    with open(p, "w") as f:
        for s, d in zip(rng.integers(0, 40, 300), rng.integers(0, 40, 300)):
            f.write(f"{s} {d}\n")
    out_b = str(tmp_path / "b.tsv")
    out_rep = str(tmp_path / "rep.tsv")
    base = ["--input", p, "--iters", "5", "--log-every", "0",
            "--dtype", "float64"]
    assert main(base + ["--vertex-sharded", "--vs-bounded",
                        "--out", out_b]) == 0
    assert main(base + ["--out", out_rep]) == 0
    ranks_b = [float(l.split("\t")[1]) for l in open(out_b)]
    ranks_rep = [float(l.split("\t")[1]) for l in open(out_rep)]
    np.testing.assert_allclose(ranks_b, ranks_rep, rtol=1e-12)


def test_vertex_sharded_snapshot_resume(tmp_path, graph):
    """SIGKILL-free resume analogue: snapshot at iter 4, restore into a
    fresh vertex-sharded engine, finish, compare to uninterrupted."""
    from pagerank_tpu.utils.snapshot import Snapshotter, resume_engine

    cfg = CFG64.replace(num_devices=8, vertex_sharded=True)
    full = JaxTpuEngine(cfg).build(graph).run()

    snap = Snapshotter(str(tmp_path), graph.fingerprint(), cfg.semantics)
    half = JaxTpuEngine(cfg.replace(num_iters=4)).build(graph)
    r4 = half.run()
    snap.save(4, r4)

    resumed = JaxTpuEngine(cfg).build(graph)
    assert resume_engine(resumed, snap) == 4
    np.testing.assert_array_equal(resumed.run(), full)
