"""Silent-data-corruption defense tests (ISSUE 15; pagerank_tpu/sdc.py;
docs/ROBUSTNESS.md "Silent data corruption"): ABFT check-value parity
vs a numpy oracle per dispatch form, every injected flip class detected
AND localized to the injected device, transient-vs-sticky
classification across the bounded redo, quarantine -> oracle-parity
finish on the degraded mesh, the persisted exclusion list, the
``--sdc-check-every 0`` bit-identity + zero-computation booby trap,
and same-seed bit-for-bit chaos reproducibility."""

import warnings

import numpy as np
import pytest

import jax

from pagerank_tpu import JaxTpuEngine, PageRankConfig, build_graph, jobs
from pagerank_tpu import sdc as sdc_mod
from pagerank_tpu.engines.cpu import ReferenceCpuEngine
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.parallel.elastic import (
    DeviceHealthMonitor,
    DeviceQuarantinedError,
    ElasticRunner,
)
from pagerank_tpu.testing.faults import (
    DeviceFaultSchedule,
    flip_rank_bit,
    install_device_faults,
    mutate_rank_shard,
)

NDEV = len(jax.devices())
EPS32 = float(np.finfo(np.float32).eps)
F32_GATE = 1e-4


def _graph(seed=7, n=1024, e=8192):
    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


def _edges(seed=7, n=1024, e=8192):
    rng = np.random.default_rng(seed)
    return rng.integers(0, n, e), rng.integers(0, n, e)


def _cfg(**kw):
    kw.setdefault("num_iters", 12)
    kw.setdefault("dtype", "float32")
    kw.setdefault("accum_dtype", "float32")
    kw.setdefault("num_devices", NDEV)
    return PageRankConfig(**kw)


def _oracle(src, dst, n, iters, semantics="reference"):
    cfg = PageRankConfig(num_iters=iters, dtype="float64",
                         accum_dtype="float64", semantics=semantics)
    return ReferenceCpuEngine(cfg).build(
        build_graph(src, dst, n=n)).run()


def _l1(ranks, oracle):
    return float(np.abs(ranks - oracle).sum()) / float(
        np.abs(oracle).sum())


def _evaluate(eng, pre, chk):
    return sdc_mod.evaluate_check(
        pre, chk, damping=eng.config.damping,
        semantics=eng.config.semantics, n=int(eng.graph.n),
        num_edges=int(eng.graph.num_edges), eps=EPS32)


# -- invariant parity vs the numpy oracle, per dispatch form ----------------


FORM_CONFIGS = {
    "step": dict(),
    "coo": dict(kernel="coo"),
    "partitioned": dict(partition_span=256),
    "vertex_sharded": dict(vertex_sharded=True),
    "vs_halo": dict(vertex_sharded=True, halo_exchange=True),
    "vs_bounded": dict(vertex_sharded=True, vs_bounded=True),
}


@pytest.mark.parametrize("form", sorted(FORM_CONFIGS))
def test_check_values_match_numpy_oracle(form):
    """The in-step ABFT values must equal a direct numpy computation
    over the engine's own (padded, relabeled) state — per dispatch
    form — and a clean step must reconcile every invariant."""
    g = _graph()
    cfg = _cfg(semantics="textbook", sdc_check_every=1,
               **FORM_CONFIGS[form])
    eng = JaxTpuEngine(cfg).build(g)
    assert eng.sdc_supported()
    for _ in range(2):
        eng.step()
        eng.iteration += 1
    r_pad = np.asarray(jax.device_get(eng._r), np.float64)
    w = sdc_mod.fingerprint_vector(0, eng._n_state)
    pre = eng.sdc_state_values()
    info, chk = eng.step_sdc()
    sharded = chk["sharded"]

    def total(v):
        return float(np.sum(v)) if sharded else float(np.median(v))

    assert total(chk["fp_in"]) == pytest.approx(float(w @ r_pad),
                                                rel=1e-4, abs=1e-6)
    assert total(chk["mass_in"]) == pytest.approx(float(r_pad.sum()),
                                                  rel=1e-5)
    assert total(chk["mass_prev"]) == pytest.approx(float(r_pad.sum()),
                                                    rel=1e-5)
    r2 = np.asarray(jax.device_get(eng._r), np.float64)
    assert total(chk["mass_out"]) == pytest.approx(float(r2.sum()),
                                                   rel=1e-5)
    assert total(chk["fp_out"]) == pytest.approx(float(w @ r2),
                                                 rel=1e-4, abs=1e-6)
    if chk["src_in"] is not None:
        inv = np.asarray(jax.device_get(eng._inv_out), np.float64)
        expect_src = float(r_pad[: inv.shape[0]][inv != 0].sum())
        assert total(chk["src_in"]) == pytest.approx(expect_src,
                                                     rel=1e-5)
        # Link conservation in exact arithmetic: sum(contrib) ==
        # sum(r[out_degree > 0]).
        assert float(np.sum(chk["contrib"])) == pytest.approx(
            expect_src, rel=1e-4)
    verdict = _evaluate(eng, pre, chk)
    assert verdict.ok, verdict.describe()
    assert info["rank_mass"] == pytest.approx(float(r2.sum()), rel=1e-5)


# -- every flip class detected + localized ----------------------------------


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device mesh")
@pytest.mark.parametrize("kind", ["mantissa", "exponent", "sign"])
def test_flip_classes_detected_and_localized_replicated(kind):
    """Each bit-flip class on one replicated copy breaches the
    invariants at the next checked step, localized to the flipped
    device position."""
    g = _graph()
    eng = JaxTpuEngine(_cfg(sdc_check_every=1)).build(g)
    for _ in range(3):
        eng.step()
        eng.iteration += 1
    pre = eng.sdc_state_values()
    flip_rank_bit(eng, device_id=int(jax.devices()[3].id), kind=kind,
                  frac=0.41)
    _info, chk = eng.step_sdc()
    verdict = _evaluate(eng, pre, chk)
    assert not verdict.ok, kind
    assert verdict.suspect == 3, (kind, verdict.describe())


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device mesh")
def test_mass_preserving_flip_detected():
    """A corruption that PRESERVES total mass (+x here, -x there) is
    invisible to the global --mass-tol scalar but not to the random
    projection: the Rademacher fingerprint of the corrupted copy
    diverges and localizes."""
    g = _graph()
    eng = JaxTpuEngine(_cfg(semantics="textbook",
                            sdc_check_every=1)).build(g)
    for _ in range(3):
        eng.step()
        eng.iteration += 1
    pre = eng.sdc_state_values()

    def mass_preserving(data, lo):
        # Move mass between two lanes whose w signs differ so the
        # projection must move; totals stay bit-comparable.
        w = sdc_mod.fingerprint_vector(0, data.size)
        i = int(np.argmax(w[:256]))
        j = int(np.argmin(w[:256]))
        x = np.float32(1e-3)
        data[i] += x
        data[j] -= x
        return data

    mutate_rank_shard(eng, int(jax.devices()[5].id), mass_preserving)
    _info, chk = eng.step_sdc()
    verdict = _evaluate(eng, pre, chk)
    assert not verdict.ok
    assert verdict.suspect == 5, verdict.describe()
    # The mass vectors agree (the flip conserved mass) — the
    # FINGERPRINT is what convicted.
    kinds = {r["kind"] for r in verdict.reasons}
    assert any(k.startswith(("copy:fp", "dual:fingerprint"))
               for k in kinds), kinds


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device mesh")
def test_sharded_flip_detected_via_dual_fingerprint():
    """On a vertex-sharded form there are no redundant copies — the
    dual-computation invariant (boundary dispatch vs in-step tail)
    catches an at-rest flip and the per-shard partial diff localizes
    the owning device."""
    g = _graph()
    eng = JaxTpuEngine(_cfg(vertex_sharded=True,
                            sdc_check_every=1)).build(g)
    for _ in range(2):
        eng.step()
        eng.iteration += 1
    pre = eng.sdc_state_values()
    flip_rank_bit(eng, device_id=int(jax.devices()[4].id),
                  kind="exponent", frac=0.5)
    _info, chk = eng.step_sdc()
    verdict = _evaluate(eng, pre, chk)
    assert not verdict.ok
    assert verdict.suspect == 4, verdict.describe()


# -- transient vs sticky classification -------------------------------------


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device mesh")
def test_transient_flip_healed_by_redo_and_oracle_parity():
    """A one-shot flip: detected, the bounded redo reconciles clean,
    the episode classifies TRANSIENT, the solve continues and the
    final ranks match the f64 oracle — the corruption never reached
    them."""
    src, dst = _edges()
    g = build_graph(src, dst, n=1024)
    sdc_mod.reset()
    eng = JaxTpuEngine(_cfg(sdc_check_every=1)).build(g)
    sched = DeviceFaultSchedule(seed=13, flip={5: (3, "exponent")})
    install_device_faults(eng, sched)
    ranks = eng.run()
    s = sdc_mod.report_section()
    assert s["flips_detected"] == 1
    assert s["transient"] == 1 and s["sticky"] == 0
    assert s["last_breach"]["classified"] == "transient"
    assert s["last_breach"]["device"] == 3
    assert s["quarantined_devices"] == []
    oracle = _oracle(src, dst, 1024, eng.config.num_iters)
    assert _l1(ranks, oracle) <= F32_GATE


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device mesh")
def test_sticky_flip_raises_quarantine():
    """A sticky flip re-fires on the redo's re-execution: the repeat
    breach attributes to the same device and the guard raises
    DeviceQuarantinedError carrying that device id."""
    g = _graph()
    sdc_mod.reset()
    eng = JaxTpuEngine(_cfg(sdc_check_every=1)).build(g)
    sched = DeviceFaultSchedule(seed=13, flip={4: (6, "mantissa")},
                                sticky_flips=[4])
    install_device_faults(eng, sched)
    with pytest.raises(DeviceQuarantinedError) as ei:
        eng.run()
    assert ei.value.device_ids == (int(jax.devices()[6].id),)
    s = sdc_mod.report_section()
    assert s["sticky"] == 1
    assert s["quarantined_devices"] == [int(jax.devices()[6].id)]


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device mesh")
def test_quarantine_finishes_on_degraded_mesh_at_oracle_gate():
    """The full machine: sticky flip -> detect -> localize -> redo ->
    sticky -> quarantine through the elastic rescue -> the solve
    FINISHES on the degraded mesh and matches the f64 oracle."""
    src, dst = _edges()
    g = build_graph(src, dst, n=1024)
    sdc_mod.reset()
    obs_metrics.get_registry().reset()
    cfg = _cfg(sdc_check_every=1)
    eng = JaxTpuEngine(cfg).build(g)
    sched = DeviceFaultSchedule(seed=11, flip={5: (2, "mantissa")},
                                sticky_flips=[5])
    install_device_faults(eng, sched)

    def factory(devs):
        return JaxTpuEngine(
            cfg.replace(num_devices=len(devs)), devices=devs
        ).build(g)

    quarantined_seen = []
    runner = ElasticRunner(
        eng, factory, snapshotter=None, max_rescues=2,
        liveness=sched.liveness_probe, monitor=DeviceHealthMonitor(),
        on_rebuild=lambda e2: install_device_faults(e2, sched),
        on_quarantine=lambda ids: quarantined_seen.append(list(ids)),
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        ranks = runner.run()
    assert runner.quarantined_device_ids == [2]
    assert quarantined_seen == [[2]]
    assert runner.rescues == 1
    assert runner.engine.mesh.devices.size == NDEV - 1
    assert 2 not in [int(d.id) for d in
                     runner.engine.mesh.devices.reshape(-1)]
    oracle = _oracle(src, dst, 1024, cfg.num_iters)
    assert _l1(ranks, oracle) <= F32_GATE
    counters = obs_metrics.get_registry().snapshot()["counters"]
    assert counters["sdc.flips_detected"] >= 1
    assert counters["sdc.quarantined_devices"] == 1


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device mesh")
def test_guard_token_never_restores_future_state():
    """Regression (review finding): after an external rewind (the
    health-check rollback), the guard's retained token can point PAST
    the live iteration — a redo must re-base on the current state, not
    jump the solve forward onto rejected state."""
    g = _graph()
    eng = JaxTpuEngine(_cfg(sdc_check_every=1)).build(g)
    guard = sdc_mod.attach_guard(eng)
    early = eng.retain_state()
    for _ in range(4):
        eng.step()
        eng.iteration += 1
    guard._token = eng.retain_state(iteration=eng.iteration)  # at 4
    # External rewind behind the token (what a rollback does): the
    # defensive re-base must keep the checked step AT the early
    # boundary — never teleport the solve to the token's iteration.
    eng.restore_state(early)
    info = guard.checked_step()
    assert eng.iteration == 0
    assert info["sdc"] == {"ok": True}
    assert guard._token[0] == 1

    # The run loop's protocol: note_rollback re-bases the double
    # buffer on the freshly RESTORED (clean) state, so a breach after
    # the rollback still heals as transient from clean state.
    eng.restore_state(early)
    guard.note_rollback()
    assert guard._token[0] == eng.iteration == 0
    flip_rank_bit(eng, device_id=int(jax.devices()[1].id),
                  kind="exponent", frac=0.3)
    info = guard.checked_step()
    assert eng.iteration == 0
    assert info["sdc"]["transient"] is True
    assert info["sdc"]["suspect_device"] == int(jax.devices()[1].id)


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device mesh")
def test_quarantine_persists_without_rescue_runner(tmp_path):
    """Regression (review finding): a sticky conviction must land in
    job.json AT conviction time — even when no elastic rescue is wired
    to survive it — so the resumed job excludes the chip."""
    g = _graph()
    sdc_mod.reset()
    job = jobs.JobSupervisor(str(tmp_path))
    sdc_mod.set_quarantine_hook(job.quarantine_devices)
    try:
        eng = JaxTpuEngine(_cfg(sdc_check_every=1)).build(g)
        sched = DeviceFaultSchedule(seed=7, flip={3: (4, "mantissa")},
                                    sticky_flips=[3])
        install_device_faults(eng, sched)
        with pytest.raises(DeviceQuarantinedError):
            eng.run()
    finally:
        sdc_mod.reset()
    assert job.quarantined_devices() == [int(jax.devices()[4].id)]
    assert jobs.JobSupervisor(str(tmp_path)).quarantined_devices() == \
        [int(jax.devices()[4].id)]


# -- exclusion list persistence ---------------------------------------------


def test_job_manifest_persists_quarantine(tmp_path):
    """The job.json exclusion list survives a supervisor restart
    (idempotent merge) — the substrate a resumed job reads to never
    re-adopt a known-bad chip."""
    job = jobs.JobSupervisor(str(tmp_path))
    assert job.quarantined_devices() == []
    job.quarantine_devices([2])
    job.quarantine_devices([5, 2])
    assert job.quarantined_devices() == [2, 5]
    job2 = jobs.JobSupervisor(str(tmp_path))
    assert job2.quarantined_devices() == [2, 5]
    assert job2.report_section()["quarantined_devices"] == [2, 5]


@pytest.mark.skipif(NDEV < 3, reason="needs >= 3 devices")
def test_rescue_honors_exclusion_list():
    """Regression (ISSUE 15 satellite): a rescue after a prior-run
    quarantine rebuilds on survivors MINUS the excluded ids — a
    device kill on an 8-device mesh with device 2 pre-quarantined
    lands on NDEV - 2 devices, neither of them the excluded chip."""
    g = _graph(n=512, e=4096)
    cfg = _cfg(num_iters=10)
    eng = JaxTpuEngine(cfg).build(g)
    sched = DeviceFaultSchedule(seed=5, kill={4: 1})
    install_device_faults(eng, sched)

    def factory(devs):
        return JaxTpuEngine(
            cfg.replace(num_devices=len(devs)), devices=devs
        ).build(g)

    runner = ElasticRunner(
        eng, factory, snapshotter=None, max_rescues=2,
        liveness=sched.liveness_probe,
        on_rebuild=lambda e2: install_device_faults(e2, sched),
        exclude_device_ids=[2],
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        runner.run()
    ids = [int(d.id) for d in runner.engine.mesh.devices.reshape(-1)]
    assert runner.engine.mesh.devices.size == NDEV - 2
    assert 1 not in ids and 2 not in ids


# -- --sdc-check-every 0: bit identity + zero computations ------------------


def test_check_every_zero_is_bit_identical_and_computation_free(
        monkeypatch):
    """The disarmed run must take the EXACT unchecked code path:
    bit-identical ranks, and ZERO check computations — every SDC entry
    point is booby-trapped to raise."""
    g = _graph()
    baseline = JaxTpuEngine(_cfg()).build(g).run()

    def boom(*a, **k):  # pragma: no cover - the trap must not spring
        raise AssertionError("SDC machinery touched on a disarmed run")

    monkeypatch.setattr(JaxTpuEngine, "_sdc_w", boom)
    monkeypatch.setattr(JaxTpuEngine, "_get_sdc_step", boom)
    monkeypatch.setattr(JaxTpuEngine, "_get_sdc_state_fn", boom)
    monkeypatch.setattr(JaxTpuEngine, "step_sdc", boom)
    monkeypatch.setattr(JaxTpuEngine, "retain_state", boom)
    monkeypatch.setattr(sdc_mod.SdcGuard, "__init__", boom)
    trapped = JaxTpuEngine(_cfg(sdc_check_every=0)).build(g).run()
    np.testing.assert_array_equal(baseline, trapped)


def test_checked_solve_matches_unchecked_on_clean_run():
    """With no fault injected, a checked solve produces the SAME ranks
    as the unchecked one (the checked step is the ledger core + local
    reductions — the update math is untouched)."""
    g = _graph()
    plain = JaxTpuEngine(_cfg()).build(g).run()
    sdc_mod.reset()
    checked = JaxTpuEngine(_cfg(sdc_check_every=3)).build(g).run()
    np.testing.assert_array_equal(plain, checked)
    s = sdc_mod.report_section()
    assert s["checks"] == 4 and s["flips_detected"] == 0


# -- reproducibility --------------------------------------------------------


@pytest.mark.skipif(NDEV < 2, reason="needs a multi-device mesh")
def test_same_seed_reproduces_chaos_bit_for_bit():
    """Two same-seed runs of the same scenario must produce identical
    fault logs (the faults.py convention) AND identical final ranks —
    detection, redo, and healing included."""
    src, dst = _edges()
    g = build_graph(src, dst, n=1024)

    def once():
        sdc_mod.reset()
        eng = JaxTpuEngine(_cfg(sdc_check_every=1)).build(g)
        sched = DeviceFaultSchedule(
            seed=23, flip={3: (1, "sign"), 7: (5, "exponent")})
        install_device_faults(eng, sched)
        ranks = eng.run()
        return list(sched.log), np.asarray(ranks)

    log_a, ranks_a = once()
    log_b, ranks_b = once()
    assert log_a == log_b
    assert any(entry[1] == "flip" for entry in log_a)
    np.testing.assert_array_equal(ranks_a, ranks_b)


# -- tolerances + fingerprint determinism -----------------------------------


def test_fingerprint_vector_deterministic_and_rademacher():
    w1 = sdc_mod.fingerprint_vector(3, 4096)
    w2 = sdc_mod.fingerprint_vector(3, 4096)
    np.testing.assert_array_equal(w1, w2)
    assert set(np.unique(w1)) == {-1.0, 1.0}
    w3 = sdc_mod.fingerprint_vector(4, 4096)
    assert not np.array_equal(w1, w3)


def test_tolerances_scale_with_dtype_and_count():
    eps64 = float(np.finfo(np.float64).eps)
    assert sdc_mod.sdc_tolerance(EPS32, 1024, 8192) > \
        sdc_mod.sdc_tolerance(eps64, 1024, 8192)
    assert sdc_mod.sdc_tolerance(EPS32, 1024, 1 << 20) > \
        sdc_mod.sdc_tolerance(EPS32, 1024, 8192)
    assert sdc_mod.copy_tolerance(EPS32, 4096) > \
        sdc_mod.copy_tolerance(EPS32, 1024)


def test_probe_and_sdc_cadences_compose():
    """Overlapping --probe-every / --sdc-check-every boundaries: the
    checked step takes the iteration and the probe commits via the
    standalone boundary path — both records exist, neither cadence is
    silently dropped."""
    from pagerank_tpu.obs.probes import ConvergenceProbes

    g = _graph()
    sdc_mod.reset()
    eng = JaxTpuEngine(_cfg(num_iters=8, sdc_check_every=4)).build(g)
    probes = ConvergenceProbes(2, topk=8)
    eng.run(probes=probes)
    assert [r["iteration"] for r in probes.history] == [1, 3, 5, 7]
    assert sdc_mod.report_section()["checks"] == 2
