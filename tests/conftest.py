"""Test config: fake 8 CPU devices so the sharded path runs without a TPU
pod (SURVEY.md §4 "Distributed without a cluster"), and enable x64 so the
float64 oracle/accumulation paths are real doubles."""

import os
import warnings

# The device-build jits donate their per-edge buffers (an HBM-capacity
# measure on TPU); the CPU backend used for tests lacks donation support
# and warns every build — pure noise here.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env may point at a TPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# A site plugin may have pinned jax_platforms programmatically (config
# beats env); re-pin to CPU before any backend initializes.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 run (-m 'not slow'); covered "
        "by the full suite and scripts/acceptance.py",
    )
