"""Perf-regression sentry (ISSUE 9; pagerank_tpu/obs/history.py):
lossless ingest of every historical result schema, content-hash
dedupe, robust (median+MAD) change detection with program-change vs
env-drift vs noise attribution, gate exit codes, strict JSON, the
trend rendering over the checked-in PERF_HISTORY.jsonl, and the live
history.* baseline-delta gauges."""

import glob
import json
import os

import pytest

from pagerank_tpu.obs import history as H
from pagerank_tpu.obs import live as obs_live
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.obs.__main__ import main as obs_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BENCH_FILES = sorted(glob.glob(os.path.join(REPO, "BENCH_r0*.json")))
MULTICHIP_FILES = sorted(glob.glob(os.path.join(REPO, "MULTICHIP*.json")))
PERF_HISTORY = os.path.join(REPO, "PERF_HISTORY.jsonl")
PERF_BUDGETS = os.path.join(REPO, "perf_budgets.json")

CPU_ENV = {
    "jax_version": "0.4.37", "jaxlib_version": "0.4.36",
    "backend": "cpu", "device_kind": "cpu", "device_count": 1,
    "x64": False, "git_rev": "abc1234", "python": "3.10.16",
    "platform": "linux-test", "process_count": 1,
}


def make_rec(eps, cost=100.0, env=CPU_ENV, leg="fast_f32", source="synth",
             accuracy=None, scale=14):
    """One synthetic couple-shaped RunRecord via the real normalizer —
    the detection tests exercise the same ingest path real artifacts
    take."""
    doc = {
        "metric": "edges_per_sec_per_chip",
        "value": eps / 2,  # pair headline; the leg under test is f32
        "unit": "edges/s/chip",
        "vs_baseline": 1.0,
        "fast_f32": {
            "value": eps,
            "vs_baseline": 1.0,
            "costs": {"step": {"bytes_per_edge": cost,
                               "seconds_per_iter": 0.1}},
        },
        "env": dict(env),
        "schema_version": 2,
        "scale": scale,
    }
    if accuracy is not None:
        doc["accuracy"] = {"config": "pair-f64",
                           "normalized_l1_vs_f64_oracle": accuracy}
    return H.normalize_result(doc, source=source)


# -- ingest: every checked-in schema, losslessly ----------------------------


def test_checked_in_artifacts_exist():
    assert len(BENCH_FILES) == 5 and len(MULTICHIP_FILES) == 6


def test_ingest_all_checked_in_files_lossless(tmp_path):
    """Every BENCH_r* and MULTICHIP* file in the repo ingests without
    error, keeps its headline values bit-exact, and lands once."""
    ledger = str(tmp_path / "ledger.jsonl")
    added, deduped = H.ingest_paths(ledger, BENCH_FILES + MULTICHIP_FILES)
    assert added == len(BENCH_FILES) + len(MULTICHIP_FILES)
    assert deduped == 0
    records = H.read_ledger(ledger)
    by_source = {r["source"]: r for r in records}
    # r01: legacy single-mode wrapper -> the f32 leg, value bit-exact.
    r01 = by_source["BENCH_r01.json"]
    src = json.load(open(BENCH_FILES[0]))
    assert r01["kind"] == "bench_single" and r01["legacy"]
    assert r01["legs"]["f32"]["edges_per_sec_per_chip"] == \
        src["parsed"]["value"]
    # r05: legacy couple wrapper -> pair + f32 legs, accuracy attached.
    r05 = by_source["BENCH_r05.json"]
    src5 = json.load(open(os.path.join(REPO, "BENCH_r05.json")))["parsed"]
    assert r05["legs"]["pair_f64"]["edges_per_sec_per_chip"] == \
        src5["value"]
    assert r05["legs"]["fast_f32"]["edges_per_sec_per_chip"] == \
        src5["fast_f32"]["value"]
    assert r05["legs"]["pair_f64"]["build_warm_s"] == src5["build_warm_s"]
    assert r05["legs"]["pair_f64"]["accuracy_l1"] == \
        src5["accuracy"]["normalized_l1_vs_f64_oracle"]
    # The promoted multichip schema: all three legs + comms + cost +
    # accuracy on the sparse leg.
    r06 = by_source["MULTICHIP_SPARSE_r06.json"]
    src6 = json.load(open(os.path.join(REPO, "MULTICHIP_SPARSE_r06.json")))
    assert r06["kind"] == "multichip"
    for key, leg in (("single_chip", "multichip_single"),
                     ("dense_exchange", "multichip_dense"),
                     ("sparse_exchange", "multichip_sparse")):
        assert r06["legs"][leg]["edges_per_sec_per_chip"] == \
            src6[key]["value"]
    assert r06["legs"]["multichip_sparse"]["comms_bytes_per_iter"] == \
        src6["sparse_exchange"]["comms"]["bytes_per_iter"]
    assert r06["legs"]["multichip_sparse"]["cost_bytes_per_edge"] == \
        src6["sparse_exchange"]["costs"]["step"]["bytes_per_edge"]
    assert r06["legs"]["multichip_sparse"]["accuracy_l1"] == \
        src6["accuracy"]["normalized_l1_vs_f64_oracle"]
    assert r06["env"]["backend"] == "cpu"
    # The dryrun wrappers ingest as their own kind (lossless: nothing
    # invents legs for a run that measured none).
    assert by_source["MULTICHIP_r05.json"]["kind"] == "multichip_dryrun"
    assert by_source["MULTICHIP_r05.json"]["legs"] == {}


def test_run_report_ingests(tmp_path):
    from pagerank_tpu import PageRankConfig
    from pagerank_tpu.obs.report import build_run_report

    report = build_run_report(
        config=PageRankConfig(),
        summary={"edges_per_sec_per_chip": 1.5e8,
                 "mean_iter_seconds": 0.2},
        costs={"step": {"bytes_per_edge": 123.0}},
    )
    rec = H.normalize_result(report, source="run_report.json")
    assert rec["kind"] == "run_report"
    leg = rec["legs"]["fast_f32"]  # default-config leg name
    assert leg["edges_per_sec_per_chip"] == 1.5e8
    assert leg["seconds_per_iter"] == 0.2
    assert leg["cost_bytes_per_edge"] == 123.0
    assert rec["env"]  # the report's own fingerprint rides along


def test_unrecognized_shape_raises():
    with pytest.raises(ValueError, match="unrecognized"):
        H.normalize_result({"hello": 1}, source="x.json")


def test_content_hash_dedupe(tmp_path):
    ledger = str(tmp_path / "ledger.jsonl")
    added, deduped = H.ingest_paths(ledger, [BENCH_FILES[0]])
    assert (added, deduped) == (1, 0)
    added, deduped = H.ingest_paths(ledger, [BENCH_FILES[0]])
    assert (added, deduped) == (0, 1)
    assert len(H.read_ledger(ledger)) == 1
    # Same content under a DIFFERENT source stays: each round is a
    # sample even when values coincide.
    doc = json.load(open(BENCH_FILES[0]))
    rec = H.normalize_result(doc, source="BENCH_other.json")
    assert H.append_record(ledger, rec)
    assert len(H.read_ledger(ledger)) == 2


def test_ledger_is_strict_json(tmp_path):
    """allow_nan=False discipline: a NaN smuggled into a result is
    stored as null, and every ledger line parses under a
    constant-rejecting JSON reader (the obs emitter contract)."""
    ledger = str(tmp_path / "ledger.jsonl")
    rec = make_rec(float("nan"), cost=float("inf"))
    assert rec["legs"]["fast_f32"].get("edges_per_sec_per_chip") is None \
        or "edges_per_sec_per_chip" not in rec["legs"]["fast_f32"]
    H.append_record(ledger, rec)

    def no_const(name):
        raise ValueError(f"non-spec JSON constant {name!r}")

    with open(ledger) as f:
        for line in f:
            json.loads(line, parse_constant=no_const)


# -- robust detection + attribution -----------------------------------------


def _records(*eps_cost_env):
    return [make_rec(e, cost=c, env=v, source=f"s{i}")
            for i, (e, c, v) in enumerate(eps_cost_env)]


BASE = [(3.50e8, 100.0, CPU_ENV), (3.52e8, 100.0, CPU_ENV),
        (3.48e8, 100.0, CPU_ENV), (3.51e8, 100.0, CPU_ENV),
        (3.49e8, 100.0, CPU_ENV)]


def test_within_noise_wobble_is_clean():
    records = _records(*BASE, (3.47e8, 100.0, CPU_ENV))
    changes = H.detect_changes(records)
    assert changes  # the series was evaluable...
    assert not [c for c in changes if c.flagged]  # ...and clean
    res = H.evaluate_gate(records)
    assert res.ok and not res.drift_warnings


def test_throughput_drop_with_cost_motion_is_program_change():
    """The injected 10% f32 drop WITH a moved cost model: flagged as a
    regression and attributed to the program."""
    records = _records(*BASE, (3.15e8, 130.0, CPU_ENV))
    flagged = [c for c in H.detect_changes(records) if c.flagged]
    drops = [c for c in flagged
             if c.metric == "edges_per_sec_per_chip"
             and c.leg == "fast_f32"]
    assert drops and drops[0].direction == "regression"
    assert drops[0].classification == "program-change"
    assert "cost model moved" in drops[0].evidence
    res = H.evaluate_gate(records)
    assert not res.ok and any("REGRESSION" in v for v in res.violations)


def test_throughput_drop_same_env_flat_cost_is_program_change():
    """Wall moved, cost flat, environment provably identical: what
    remains is the code axis (obs report's 'code or load' banner)."""
    records = _records(*BASE, (3.15e8, 100.0, CPU_ENV))
    drops = [c for c in H.detect_changes(records)
             if c.flagged and c.metric == "edges_per_sec_per_chip"]
    assert drops and drops[0].classification == "program-change"
    assert "environment identical" in drops[0].evidence


def test_jax_version_only_drift_is_env_drift_and_passes_gate():
    """Wall moved, cost model flat, jax/jaxlib fingerprint drifted:
    classified env-drift — a warning, never a gate failure."""
    drift_env = dict(CPU_ENV, jax_version="0.5.0", jaxlib_version="0.5.0")
    records = _records(*BASE, (3.15e8, 100.0, drift_env))
    drops = [c for c in H.detect_changes(records)
             if c.flagged and c.metric == "edges_per_sec_per_chip"]
    assert drops and drops[0].classification == "env-drift"
    assert "jax_version" in drops[0].evidence
    res = H.evaluate_gate(records)
    assert res.ok
    assert any("DRIFT" in w for w in res.drift_warnings)


def test_improvement_is_reported_not_gated():
    records = _records(*BASE, (4.3e8, 100.0, CPU_ENV))
    res = H.evaluate_gate(records)
    assert res.ok and any("improvement" in i.lower() or "+"
                          in i for i in res.improvements)


def test_min_samples_handling():
    """Two baseline points cannot define noise: no flag, whatever the
    delta — the gate notes it instead of guessing."""
    records = _records(*BASE[:2], (1.0e8, 100.0, CPU_ENV))
    assert H.detect_changes(records) == []
    res = H.evaluate_gate(records)
    assert res.ok


def test_baselines_never_mix_env_classes():
    """A CPU record is not a regression of a TPU series (the r5
    hand-separation, structural): different (backend, device_kind)
    classes do not baseline each other, and legacy fingerprint-less
    records only baseline other legacy records."""
    tpu_env = dict(CPU_ENV, backend="tpu", device_kind="TPU v5e")
    records = _records(*[(e, c, tpu_env) for e, c, _ in BASE],
                       (1.0e7, 100.0, CPU_ENV))
    assert H.detect_changes(records) == []  # no same-class history


def test_direction_awareness_build_seconds():
    """build_s is an 'up is bad' metric: the same relative move flips
    direction."""
    docs = []
    for i, b in enumerate((30.0, 30.5, 29.8, 30.2, 30.1, 45.0)):
        doc = {
            "metric": "edges_per_sec_per_chip", "value": 2.6e8,
            "unit": "edges/s/chip", "vs_baseline": 1.0,
            "fast_f32": {"value": 3.5e8, "build_s": b},
            "env": dict(CPU_ENV), "schema_version": 2,
        }
        docs.append(H.normalize_result(doc, source=f"b{i}"))
    flagged = [c for c in H.detect_changes(docs)
               if c.flagged and c.metric == "build_s"]
    assert flagged and flagged[0].direction == "regression"


# -- budgets + gate CLI -----------------------------------------------------


def test_budget_floor_violation_fails_gate(tmp_path):
    records = _records(*BASE)
    budgets = {"budgets": [
        {"leg": "fast_f32", "metric": "edges_per_sec_per_chip",
         "min": 4.0e8, "env": {"backend": "cpu"}},
    ]}
    res = H.evaluate_gate(records, budgets)
    assert not res.ok and "below budget min" in res.violations[0]


def test_env_scoped_budget_skips_other_backends():
    """A TPU floor never fires on a CPU record — and never on a legacy
    record whose fingerprint was never written."""
    budgets = {"budgets": [
        {"leg": "fast_f32", "metric": "edges_per_sec_per_chip",
         "min": 9.9e9, "env": {"backend": "tpu"}},
    ]}
    assert H.evaluate_gate(_records(*BASE), budgets).ok
    legacy = H.normalize_result(
        json.load(open(os.path.join(REPO, "BENCH_r05.json"))),
        source="BENCH_r05.json")
    assert H.evaluate_gate([legacy], budgets).ok


def test_accuracy_budget_ceiling():
    rec = make_rec(3.5e8, accuracy=1e-3)
    budgets = {"budgets": [
        {"leg": "pair_f64", "metric": "accuracy_l1", "max": 1e-6},
    ]}
    res = H.evaluate_gate([rec], budgets)
    assert not res.ok and "above budget max" in res.violations[0]


def test_gate_cli_exit_codes(tmp_path, capsys):
    ledger = str(tmp_path / "ledger.jsonl")
    for r in _records(*BASE):
        H.append_record(ledger, r)
    assert obs_main(["history", "gate", ledger]) == 0
    H.append_record(ledger, make_rec(3.0e8, cost=140.0, source="drop"))
    assert obs_main(["history", "gate", ledger]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "program-change" in out
    assert obs_main(["history", "gate", str(tmp_path / "x"),
                     "--budgets", str(tmp_path / "missing.json")]) == 2


def test_gate_cli_json(tmp_path, capsys):
    ledger = str(tmp_path / "ledger.jsonl")
    for r in _records(*BASE, (3.0e8, 140.0, CPU_ENV)):
        H.append_record(ledger, r)
    rc = obs_main(["history", "gate", ledger, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["ok"] is False
    assert any(c["flagged"] for c in doc["changes"])


def test_ingest_cli(tmp_path, capsys):
    ledger = str(tmp_path / "ledger.jsonl")
    rc = obs_main(["history", "ingest", ledger] + BENCH_FILES)
    assert rc == 0
    assert "ingested 5 record(s)" in capsys.readouterr().out
    rc = obs_main(["history", "ingest", ledger, BENCH_FILES[0],
                   "--json"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out) == \
        {"added": 0, "deduped": 1}


# -- the checked-in ledger + budgets ----------------------------------------


def test_checked_in_perf_history_renders_every_leg(capsys):
    """The ISSUE-9 acceptance rendering: `trend PERF_HISTORY.jsonl`
    carries EVERY leg with its edges/s/chip series — the r1-r5
    single-chip rounds (pair-f64 + f32), the partition-centric legs,
    and the promoted multichip dense/sparse legs. The r1->r5 f32
    plateau is mechanically present."""
    assert os.path.exists(PERF_HISTORY), "PERF_HISTORY.jsonl not checked in"
    rc = obs_main(["history", "trend", PERF_HISTORY])
    out = capsys.readouterr().out
    assert rc == 0
    for leg in ("pair_f64", "f32", "fast_f32", "partitioned_f32",
                "fast_bf16", "multichip_dense", "multichip_sparse",
                "multichip_single"):
        assert f"{leg} edges/s/chip" in out, (leg, out)
    # The plateau read: r01's f32 cell and r05's fast_f32 cell both
    # render at the known ~3.5e8 values.
    assert "r01=3.478e+08" in out
    assert "r05=3.534e+08" in out
    # ISSUE 10 satellite: scaling_efficiency renders for the multichip
    # legs ALREADY in the checked-in ledger — the pre-ISSUE-10 records
    # carry it only under extras, and metric_value reads both
    # spellings (no re-ingest, no forked series).
    assert "multichip_sparse scaling eff" in out
    assert "multichip_dense scaling eff" in out


def test_scaling_efficiency_normalizes_into_legs():
    """Fresh ingest of MULTICHIP_SPARSE_r06.json lands
    scaling_efficiency ON the multichip legs (the ISSUE-10
    normalization), agreeing with the artifact's top-level fields and
    with the extras back-compat read."""
    src = json.load(open(os.path.join(REPO, "MULTICHIP_SPARSE_r06.json")))
    rec = H.normalize_result(src, source="MULTICHIP_SPARSE_r06.json")
    assert rec["legs"]["multichip_sparse"]["scaling_efficiency"] == \
        src["scaling_efficiency"]
    assert rec["legs"]["multichip_dense"]["scaling_efficiency"] == \
        src["scaling_efficiency_dense"]
    # And the extras-only (pre-ISSUE-10 ledger) spelling reads through
    # metric_value identically.
    old = dict(rec, legs={
        leg: {k: v for k, v in m.items() if k != "scaling_efficiency"}
        for leg, m in rec["legs"].items()
    })
    assert H.metric_value(old, "multichip_sparse",
                          "scaling_efficiency") == \
        src["scaling_efficiency"]
    assert H.metric_value(old, "multichip_dense",
                          "scaling_efficiency") == \
        src["scaling_efficiency_dense"]


def test_attribution_block_normalizes_into_leg_metrics():
    """A bench leg's attribution block (ISSUE 10) lands as the
    exchange_fraction / comms_achieved_bytes_per_sec leg metrics, so
    the r06+ trend carries the exchange-bound verdict."""
    src = json.load(open(os.path.join(REPO, "MULTICHIP_SPARSE_r06.json")))
    doc = json.loads(json.dumps(src))
    doc["sparse_exchange"]["attribution"] = {
        "iters": 10, "exchange_s": 0.002, "step_s": 0.005,
        "compute_s": 0.003, "exchange_fraction": 0.4,
        "model_bytes_per_iter": 5424,
        "achieved_bytes_per_sec": 2.7e6, "mode": "sparse",
    }
    rec = H.normalize_result(doc, source="MULTICHIP_ATTR.json")
    leg = rec["legs"]["multichip_sparse"]
    assert leg["exchange_fraction"] == 0.4
    assert leg["comms_achieved_bytes_per_sec"] == 2.7e6
    assert "exchange_fraction" in H.LEG_METRICS
    assert H.METRIC_BAD_DIRECTION["scaling_efficiency"] == "down"
    assert H.METRIC_BAD_DIRECTION["exchange_fraction"] == "up"


def test_ppr_serve_phase_decomposition_normalizes_into_leg():
    """A ppr_serve bench doc's phase_p99_ms decomposition (ISSUE 19
    query plane) lands as *_p99_ms columns on the ppr_serve leg, and
    every leg column is a known, direction-tagged LEG_METRICS entry."""
    doc = {
        "metric": "ppr_serve_queries_per_sec", "value": 123.4,
        "unit": "queries/s", "p50_ms": 12.0, "p99_ms": 80.0,
        "phase_p99_ms": {"admission_wait": 1.5, "batch_wait": 40.0,
                         "dispatch": 30.0, "fetch": 2.0},
        "shed_fraction": 0.05, "rescues": 1, "queries": 200,
        "answered": 190, "outcomes": {"answered": 190, "shed": 10},
        "elapsed_s": 1.6, "offered_qps": 125.0, "scale": 12,
        "iters": 10, "edge_factor": 16, "max_batch": 8,
        "deadline_ms": 500.0, "queue_depth": 64, "topk": 64,
        "env": {"backend": "cpu"}, "schema_version": 2,
    }
    rec = H.normalize_result(doc, source="BENCH_SERVE.json")
    assert rec["kind"] == "bench_ppr_serve"
    leg = rec["legs"]["ppr_serve"]
    assert leg["admission_wait_p99_ms"] == 1.5
    assert leg["batch_wait_p99_ms"] == 40.0
    assert leg["dispatch_p99_ms"] == 30.0
    assert leg["fetch_p99_ms"] == 2.0
    assert leg["queries_per_sec"] == 123.4
    assert leg["p99_ms"] == 80.0
    for col in leg:
        assert col in H.LEG_METRICS
        # Latency legs regress UP: a taller tail is the bad direction.
        if col.endswith("_ms"):
            assert H.METRIC_BAD_DIRECTION[col] == "up"
    # Decomposition absent (pre-ISSUE-19 artifact): leg still forms,
    # just without the phase columns — old ledgers keep ingesting.
    old = {k: v for k, v in doc.items() if k != "phase_p99_ms"}
    legacy = H.normalize_result(old, source="BENCH_SERVE_OLD.json")
    assert "admission_wait_p99_ms" not in legacy["legs"]["ppr_serve"]
    assert legacy["legs"]["ppr_serve"]["p99_ms"] == 80.0


def test_checked_in_ledger_records_are_deduped_and_versioned():
    records = H.read_ledger(PERF_HISTORY)
    hashes = [r["content_hash"] for r in records]
    assert len(hashes) == len(set(hashes))
    assert all(r["schema_version"] == H.LEDGER_SCHEMA_VERSION
               for r in records)
    legs = {leg for r in records for leg in r["legs"]}
    assert {"pair_f64", "f32", "fast_f32", "partitioned_f32",
            "fast_bf16", "multichip_dense", "multichip_sparse"} <= legs


def test_checked_in_gate_passes(capsys):
    """The standing CI gate over the checked-in ledger and budgets
    must pass — this is the state every future TPU session is gated
    against."""
    assert os.path.exists(PERF_BUDGETS), "perf_budgets.json not checked in"
    rc = obs_main(["history", "gate", PERF_HISTORY,
                   "--budgets", PERF_BUDGETS])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "PASS" in out


def test_checked_in_budgets_catch_injected_regression(tmp_path):
    """A synthetic 'next TPU session regressed' record against the
    checked-in budgets: the TPU floor fires only on a TPU-classed
    record."""
    budgets = H.load_budgets(PERF_BUDGETS)
    tpu_env = dict(CPU_ENV, backend="tpu", device_kind="TPU v5e")
    slow = make_rec(1.0e8, env=tpu_env, scale=23)  # under the 3.0e8 floor
    records = H.read_ledger(PERF_HISTORY) + [slow]
    res = H.evaluate_gate(records, budgets)
    assert not res.ok
    assert any("fast_f32" in v for v in res.violations)
    # The SAME slow rate at smoke scale is out of the floors' scope
    # (min_scale): throughput budgets are headline-geometry statements.
    small = make_rec(1.0e8, env=tpu_env, scale=14)
    assert H.evaluate_gate(H.read_ledger(PERF_HISTORY) + [small],
                           budgets).ok


# -- obs report --against-history -------------------------------------------


def test_report_against_history(tmp_path, capsys):
    from pagerank_tpu import PageRankConfig
    from pagerank_tpu.obs.report import build_run_report, write_run_report

    ledger = str(tmp_path / "ledger.jsonl")
    for r in _records(*BASE):
        H.append_record(ledger, r)
    report = build_run_report(
        config=PageRankConfig(),
        summary={"edges_per_sec_per_chip": 3.0e8,
                 "mean_iter_seconds": 0.1},
        costs={"step": {"bytes_per_edge": 100.0}},
    )
    path = str(tmp_path / "run_report.json")
    write_run_report(path, report)
    rc = obs_main(["report", path, "--against-history", ledger])
    out = capsys.readouterr().out
    assert rc == 0
    assert "against history: leg 'fast_f32'" in out
    # The env-drift-first rendering is reused verbatim: the banner
    # line about environment and the rate-delta section both appear.
    assert "environment" in out
    assert "rate deltas:" in out
    # Unknown-leg ledger: clean usage error, not a traceback.
    rc = obs_main(["report", path, "--against-history",
                   str(tmp_path / "empty.jsonl")])
    assert rc == 2


# -- live baseline-delta gauges ---------------------------------------------


def test_history_gauges_published_when_armed():
    reg = obs_metrics.get_registry()
    reg.reset()
    obs_live.arm_history_baseline(obs_live.HistoryBaseline(
        leg="fast_f32", baseline_eps=2.0e8, num_edges=1_000_000,
        num_chips=1, n_baseline=5))
    try:
        # 1M edges in 10ms = 1e8 edges/s/chip = -50% vs baseline.
        obs_live.update_solve_gauges(0, {"l1_delta": 0.1}, seconds=0.01)
        gauges = reg.snapshot()["gauges"]
        assert gauges["history.baseline_edges_per_sec_per_chip"] == 2.0e8
        assert gauges["history.edges_per_sec_per_chip"] == \
            pytest.approx(1.0e8)
        assert gauges["history.vs_baseline_pct"] == pytest.approx(-50.0)
        text = obs_live.render_prometheus(reg)
        assert "pagerank_history_vs_baseline_pct" in text
    finally:
        obs_live.disarm_history_baseline()
        reg.reset()


def test_history_gauges_silent_when_disarmed():
    reg = obs_metrics.get_registry()
    reg.reset()
    obs_live.disarm_history_baseline()
    obs_live.update_solve_gauges(0, {}, seconds=0.01)
    assert not any(n.startswith("history.")
                   for n in reg.snapshot()["gauges"])
    reg.reset()


def test_leg_name_for_config_vocabulary():
    from pagerank_tpu import PageRankConfig

    assert H.leg_name_for_config(PageRankConfig()) == "fast_f32"
    assert H.leg_name_for_config(PageRankConfig(
        dtype="float64", accum_dtype="float64", wide_accum="pair",
    )) == "pair_f64"
    assert H.leg_name_for_config(PageRankConfig(
        partition_span=512)) == "partitioned_f32"
    assert H.leg_name_for_config(PageRankConfig(
        partition_span=512, stream_dtype="bfloat16")) == "fast_bf16"
    assert H.leg_name_for_config(PageRankConfig(
        vertex_sharded=True)) == "multichip_dense"
    assert H.leg_name_for_config(PageRankConfig(
        vertex_sharded=True, halo_exchange=True)) == "multichip_sparse"
    # The fused Mosaic kernel leg (ISSUE 16): kernel='pallas' on a
    # partitioned span is its OWN series — comparing it against the
    # XLA partitioned_f32 pipeline is the point of the ledger entry.
    # Without a span the pallas request alone doesn't rename the leg
    # (the engine runs the plain layout and may downgrade anyway).
    assert H.leg_name_for_config(PageRankConfig(
        kernel="pallas", partition_span=512)) == "pallas_partitioned_f32"
    assert H.leg_name_for_config(PageRankConfig(
        kernel="pallas")) == "fast_f32"
    assert H._leg_name_from_layout(
        {"form": "pallas_partitioned", "kernel": "pallas_part:take",
         "partition_span": 512}) == "pallas_partitioned_f32"
    # f64 naming must agree with _leg_name_from_layout's vocabulary:
    # the CLI can't set wide_accum (stays "auto", pair on TPU), so its
    # f64 runs join the headline pair_f64 series; only explicit NATIVE
    # wide accumulation is the separate "f64" series.
    assert H.leg_name_for_config(PageRankConfig(
        dtype="float64", accum_dtype="float64")) == "pair_f64"
    assert H.leg_name_for_config(PageRankConfig(
        dtype="float64", accum_dtype="float64",
        wide_accum="native")) == "f64"
    assert H._leg_name_from_layout(
        {"pair": True, "accum_dtype": "float64"}) == "pair_f64"
    assert H._leg_name_from_layout(
        {"pair": False, "accum_dtype": "float64"}) == "f64"


def test_cli_help_renders_with_history_flag():
    """argparse %-formats help strings: a bare '%' in the --history
    help crashed `--help` with ValueError (review finding)."""
    from pagerank_tpu.cli import build_parser

    assert "--history" in build_parser().format_help()


def test_unreadable_ledger_raises_not_empty(tmp_path):
    """A ledger that exists but can't be read as a file must RAISE —
    a CI gate going green on an IsADirectoryError would be the silent
    failure this module exists to prevent. Only a MISSING path reads
    as the empty ledger."""
    d = tmp_path / "ledger_dir"
    d.mkdir()
    with pytest.raises(OSError):
        H.read_ledger(str(d))
    assert H.read_ledger(str(tmp_path / "missing.jsonl")) == []


def test_gate_missing_ledger_is_usage_error(tmp_path, capsys):
    """trend/gate on a mistyped ledger path exit 2, never PASS-on-
    empty; ingest still creates a fresh ledger."""
    missing = str(tmp_path / "nope.jsonl")
    assert obs_main(["history", "gate", missing]) == 2
    assert obs_main(["history", "trend", missing]) == 2
    capsys.readouterr()
    assert obs_main(["history", "ingest", missing, BENCH_FILES[0]]) == 0
