"""Pallas ELL SpMV kernel (ops/pallas_spmv.py) vs the numpy oracle —
interpret mode (the Mosaic-compiled path needs real TPU hardware; the
kernel's logic, shapes and RMW accumulation are validated here)."""

import numpy as np
import pytest

import jax.numpy as jnp

from pagerank_tpu import build_graph
from pagerank_tpu.graph import inv_out_degree, to_csr_transpose
from pagerank_tpu.ops import ell as ell_lib
from pagerank_tpu.ops import pallas_spmv


def _sentinel_form(pack, chunk):
    """Engine-style slot prep: inert slots -> sentinel, rows padded to a
    chunk multiple, per-chunk first-block ids."""
    n_state = pack.n_padded
    src = np.where(pack.weight != 0, pack.src, np.int32(n_state))
    rows = src.shape[0]
    target = max(chunk, -(-rows // chunk) * chunk)
    pad = target - rows
    src = np.concatenate([src, np.full((pad, 128), n_state, np.int32)])
    rb = np.concatenate([
        pack.row_block,
        np.full(pad, max(0, pack.num_blocks - 1), np.int32),
    ])
    rb0 = rb[::chunk].copy()
    return src, rb, rb0


@pytest.mark.parametrize("gather", ["take", "onehot8"])
@pytest.mark.parametrize("chunk", [8, 32])
def test_pallas_matches_csr_oracle(gather, chunk):
    rng = np.random.default_rng(0)
    n, e = 500, 4000
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    pack = ell_lib.ell_pack(g)
    src, rb, rb0 = _sentinel_form(pack, chunk)

    r = rng.random(n).astype(np.float32)
    inv = inv_out_degree(g.out_degree, dtype=np.float64)[pack.perm]
    z = np.zeros(pack.n_padded + 8, np.float32)
    z[: g.n] = r[pack.perm] * inv[: g.n]

    y = pallas_spmv.ell_contrib_pallas(
        jnp.asarray(z), jnp.asarray(src), jnp.asarray(rb), jnp.asarray(rb0),
        pack.num_blocks, chunk=chunk, gather=gather, interpret=True,
    )
    y = np.asarray(y)

    expected_orig = to_csr_transpose(g) @ r.astype(np.float64)
    got = np.empty(g.n, np.float64)
    got[pack.perm] = y[: g.n]
    np.testing.assert_allclose(got, expected_orig, rtol=2e-6, atol=2e-7)


@pytest.mark.parametrize("gather", ["take", "onehot8"])
def test_pallas_matches_ell_contrib_op(gather):
    """Direct parity (ISSUE 6 satellite): ell_contrib_pallas (interpret
    mode, both gather strategies) against the XLA ell_contrib op on
    IDENTICAL sentinel-form inputs — the tightest guard against rot in
    a kernel Mosaic currently refuses to compile on hardware (it runs
    here in interpret mode only)."""
    from pagerank_tpu.ops import spmv

    rng = np.random.default_rng(7)
    n, e, chunk = 700, 6000, 16
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    pack = ell_lib.ell_pack(g)
    src, rb, rb0 = _sentinel_form(pack, chunk)

    z = np.zeros(pack.n_padded + 8, np.float32)
    z[: g.n] = rng.random(g.n).astype(np.float32)

    y_pallas = np.asarray(pallas_spmv.ell_contrib_pallas(
        jnp.asarray(z), jnp.asarray(src), jnp.asarray(rb),
        jnp.asarray(rb0), pack.num_blocks, chunk=chunk, gather=gather,
        interpret=True,
    ))
    y_ell = np.asarray(spmv.ell_contrib(
        jnp.asarray(z), jnp.asarray(src), jnp.asarray(rb),
        pack.num_blocks, gather_width=8, chunk_rows=None, group=1,
    ))
    np.testing.assert_allclose(y_pallas, y_ell, rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("ndev", [1, 2])
def test_engine_pallas_kernel_matches_oracle(ndev):
    # Full engine with kernel="pallas" (interpret mode on CPU) vs the
    # f64 oracle; also exercises the sharded per-device rb0 slicing.
    from pagerank_tpu import JaxTpuEngine, PageRankConfig, ReferenceCpuEngine

    rng = np.random.default_rng(21)
    n, e = 400, 3000
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    cfg = PageRankConfig(
        num_iters=8, kernel="pallas", dtype="float64", accum_dtype="float64",
        num_devices=ndev,
    )
    eng = JaxTpuEngine(cfg).build(g)
    assert eng._kernel.startswith("pallas")
    r_p = eng.run()
    r_cpu = ReferenceCpuEngine(cfg).build(g).run()
    np.testing.assert_allclose(r_p, r_cpu, rtol=0, atol=1e-12)


def test_engine_pallas_vmem_budget_downgrades():
    """ISSUE-16 satellite: when the rank vector exceeds the shared
    PTK001 VMEM budget (obs/costs.pallas_vmem_budget) and no
    partition_span is set, the legacy whole-z kernel must raise a
    clean PallasUnavailableError INSIDE the build — and the engine
    must downgrade to the native ell layout and finish building,
    recording the request. (This replaced the old hard ValueError:
    a refused build cost campaigns a crash where a slower leg was
    available.)"""
    from pagerank_tpu import JaxTpuEngine, PageRankConfig

    rng = np.random.default_rng(2)
    n = 1 << 21  # 2M vertices * f64 > the ~12MB default budget
    g = build_graph(rng.integers(0, n, 1000), rng.integers(0, n, 1000), n=n)
    cfg = PageRankConfig(kernel="pallas", dtype="float64",
                         accum_dtype="float64", num_devices=1)
    eng = JaxTpuEngine(cfg).build(g)
    assert not eng._kernel.startswith("pallas")
    assert eng.layout_info()["kernel_requested"] == "pallas"


def _partitioned_form(rng, *, K=2, psz=256, chunk=128, width=16,
                      rows_per_part=256, gw=128):
    """Random toy partition-centric layout in the engine's ISSUE-6
    form: partition-LOCAL slot indices with sentinel ``psz``, dense
    chunk-local pair ranks, per-chunk (partition, first-rank) bases,
    plus the flat partition-padded z table the XLA window path
    consumes and an f64 numpy oracle."""
    rows = K * rows_per_part
    nc = rows // chunk
    pairs = nc * (width // 2)
    src = rng.integers(0, psz + 1, (rows, 128)).astype(np.int32)
    rk_g = ((np.arange(rows) * pairs) // rows).astype(np.int32)
    rb0 = rk_g[::chunk].copy()
    rk_loc = (rk_g - np.repeat(rb0, chunk)).astype(np.int32)
    part_ids = np.repeat(np.arange(K, dtype=np.int32),
                         rows_per_part // chunk)
    bases = np.stack([part_ids, rb0], 1).astype(np.int32)
    win_rows = (psz + gw) // 128
    zt = np.zeros((K, win_rows * 128), np.float32)
    zt[:, :psz] = rng.random((K, psz)).astype(np.float32)

    y64 = np.zeros((pairs, 128))
    for r in range(rows):
        p = part_ids[r // chunk]
        y64[rk_g[r]] += zt[p].astype(np.float64)[src[r]]
    return dict(src=src, rk_loc=rk_loc, bases=bases, zt=zt,
                win_rows=win_rows, pairs=pairs, chunk=chunk,
                width=width, oracle=y64.reshape(-1), part_ids=part_ids)


@pytest.mark.parametrize("gather", ["take", "onehot8"])
@pytest.mark.parametrize("words24", [False, True])
def test_pallas_partitioned_matches_oracle(gather, words24):
    """ISSUE-16 payload: the partitioned kernel (interpret mode) vs
    the f64 numpy oracle — both Mosaic gather strategies, both slot
    word encodings (3-byte planar int8 and int32)."""
    from pagerank_tpu.ops import spmv

    rng = np.random.default_rng(5)
    f = _partitioned_form(rng)
    K, win_rows = f["zt"].shape[0], f["win_rows"]
    zw = jnp.asarray(f["zt"].reshape(K, win_rows, 128))
    src = jnp.asarray(f["src"])
    if words24:
        src = spmv.pack_words24(src, jnp)
    y = np.asarray(pallas_spmv.ell_contrib_pallas_partitioned(
        zw, src, jnp.asarray(f["rk_loc"].reshape(-1, 128)),
        jnp.asarray(f["bases"]), f["pairs"], chunk=f["chunk"],
        width=f["width"], gather=gather, interpret=True,
    ))
    np.testing.assert_allclose(y, f["oracle"], rtol=2e-6, atol=2e-7)


@pytest.mark.parametrize("gather", ["take", "onehot8"])
def test_pallas_partitioned_bitwise_matches_ell_contrib(gather):
    """f32 BIT-FOR-BIT parity against the XLA window-mode ell_contrib
    on identical inputs with MATCHED chunking (same one-hot dot
    contraction order). This is the rot guard for a kernel Mosaic can
    only compile on hardware: any change to the gather, the one-hot
    segment matmul, or the RMW accumulation order shows up as a
    single-ulp diff here."""
    from pagerank_tpu.ops import spmv

    rng = np.random.default_rng(9)
    f = _partitioned_form(rng)
    K, win_rows = f["zt"].shape[0], f["win_rows"]
    y_pallas = np.asarray(pallas_spmv.ell_contrib_pallas_partitioned(
        jnp.asarray(f["zt"].reshape(K, win_rows, 128)),
        jnp.asarray(f["src"]),
        jnp.asarray(f["rk_loc"].reshape(-1, 128)),
        jnp.asarray(f["bases"]), f["pairs"], chunk=f["chunk"],
        width=f["width"], gather=gather, interpret=True,
    ))
    cb = np.stack([f["part_ids"] * win_rows, f["bases"][:, 1]],
                  1).astype(np.int32)
    y_ell = np.asarray(spmv.ell_contrib(
        jnp.asarray(f["zt"].reshape(-1)), jnp.asarray(f["src"]),
        jnp.asarray(f["rk_loc"]), f["pairs"], gather_width=128,
        chunk_rows=f["chunk"], group=1, num_present=f["pairs"],
        window_rows=win_rows, chunk_bases=jnp.asarray(cb),
    ))
    assert np.array_equal(y_pallas, y_ell)


def test_pallas_partitioned_bf16_stream_vs_f64_oracle():
    """bf16 z window stream, f32 accumulation: the error against the
    f64 oracle must stay within the bf16 mantissa bound (~2^-8
    relative per gathered value; sums are f32-exact on top)."""
    rng = np.random.default_rng(13)
    f = _partitioned_form(rng)
    K, win_rows = f["zt"].shape[0], f["win_rows"]
    zw = jnp.asarray(f["zt"].reshape(K, win_rows, 128), jnp.bfloat16)
    y = np.asarray(pallas_spmv.ell_contrib_pallas_partitioned(
        zw, jnp.asarray(f["src"]),
        jnp.asarray(f["rk_loc"].reshape(-1, 128)),
        jnp.asarray(f["bases"]), f["pairs"], chunk=f["chunk"],
        width=f["width"], gather="take", interpret=True,
    ))
    assert y.dtype == np.float32
    scale = np.abs(f["oracle"]).max()
    np.testing.assert_allclose(y, f["oracle"], rtol=2**-7,
                               atol=2**-8 * scale)


@pytest.mark.parametrize("ndev", [1, 2])
def test_engine_pallas_partitioned_matches_oracle(ndev):
    """Full engine on the ISSUE-16 payload path: kernel='pallas' WITH
    partition_span routes to ell_contrib_pallas_partitioned (interpret
    mode on CPU) — the windowed-stream kernel, not the legacy whole-z
    one — and must match the CPU reference at f32 iteration noise."""
    from pagerank_tpu import JaxTpuEngine, PageRankConfig, ReferenceCpuEngine

    rng = np.random.default_rng(31)
    n, e = 400, 3000
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    cfg = PageRankConfig(num_iters=8, kernel="pallas", partition_span=256,
                         num_devices=ndev)
    eng = JaxTpuEngine(cfg).build(g)
    assert eng._kernel.startswith("pallas_part")
    assert eng.layout_info()["form"] == "pallas_partitioned"
    r_p = eng.run()
    r_cpu = ReferenceCpuEngine(cfg).build(g).run()
    np.testing.assert_allclose(r_p, r_cpu, rtol=1e-5, atol=1e-7)


def test_pallas_block_boundary_accumulation():
    # A single dst block whose rows span many chunks: every chunk RMWs
    # the same output rows — the donated-zeros + accumulate path.
    n = 64  # one 128-block after padding
    e_per = 40
    src = np.repeat(np.arange(32), e_per)  # 32 sources
    dst = np.tile(np.arange(32), e_per)
    g = build_graph(src, dst, n=n, dedup=False)
    pack = ell_lib.ell_pack(g)
    chunk = 8
    s, rb, rb0 = _sentinel_form(pack, chunk)
    rng = np.random.default_rng(1)
    r = rng.random(n).astype(np.float32)
    inv = inv_out_degree(g.out_degree, dtype=np.float64)[pack.perm]
    z = np.zeros(pack.n_padded + 8, np.float32)
    z[: g.n] = r[pack.perm] * inv[: g.n]
    y = pallas_spmv.ell_contrib_pallas(
        jnp.asarray(z), jnp.asarray(s), jnp.asarray(rb), jnp.asarray(rb0),
        pack.num_blocks, chunk=chunk, gather="take", interpret=True,
    )
    got = np.empty(g.n, np.float64)
    got[pack.perm] = np.asarray(y)[: g.n]
    expected = to_csr_transpose(g) @ r.astype(np.float64)
    np.testing.assert_allclose(got, expected, rtol=2e-6, atol=2e-7)
