"""Pallas ELL SpMV kernel (ops/pallas_spmv.py) vs the numpy oracle —
interpret mode (the Mosaic-compiled path needs real TPU hardware; the
kernel's logic, shapes and RMW accumulation are validated here)."""

import numpy as np
import pytest

import jax.numpy as jnp

from pagerank_tpu import build_graph
from pagerank_tpu.graph import inv_out_degree, to_csr_transpose
from pagerank_tpu.ops import ell as ell_lib
from pagerank_tpu.ops import pallas_spmv


def _sentinel_form(pack, chunk):
    """Engine-style slot prep: inert slots -> sentinel, rows padded to a
    chunk multiple, per-chunk first-block ids."""
    n_state = pack.n_padded
    src = np.where(pack.weight != 0, pack.src, np.int32(n_state))
    rows = src.shape[0]
    target = max(chunk, -(-rows // chunk) * chunk)
    pad = target - rows
    src = np.concatenate([src, np.full((pad, 128), n_state, np.int32)])
    rb = np.concatenate([
        pack.row_block,
        np.full(pad, max(0, pack.num_blocks - 1), np.int32),
    ])
    rb0 = rb[::chunk].copy()
    return src, rb, rb0


@pytest.mark.parametrize("gather", ["take", "onehot8"])
@pytest.mark.parametrize("chunk", [8, 32])
def test_pallas_matches_csr_oracle(gather, chunk):
    rng = np.random.default_rng(0)
    n, e = 500, 4000
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    pack = ell_lib.ell_pack(g)
    src, rb, rb0 = _sentinel_form(pack, chunk)

    r = rng.random(n).astype(np.float32)
    inv = inv_out_degree(g.out_degree, dtype=np.float64)[pack.perm]
    z = np.zeros(pack.n_padded + 8, np.float32)
    z[: g.n] = r[pack.perm] * inv[: g.n]

    y = pallas_spmv.ell_contrib_pallas(
        jnp.asarray(z), jnp.asarray(src), jnp.asarray(rb), jnp.asarray(rb0),
        pack.num_blocks, chunk=chunk, gather=gather, interpret=True,
    )
    y = np.asarray(y)

    expected_orig = to_csr_transpose(g) @ r.astype(np.float64)
    got = np.empty(g.n, np.float64)
    got[pack.perm] = y[: g.n]
    np.testing.assert_allclose(got, expected_orig, rtol=2e-6, atol=2e-7)


@pytest.mark.parametrize("gather", ["take", "onehot8"])
def test_pallas_matches_ell_contrib_op(gather):
    """Direct parity (ISSUE 6 satellite): ell_contrib_pallas (interpret
    mode, both gather strategies) against the XLA ell_contrib op on
    IDENTICAL sentinel-form inputs — the tightest guard against rot in
    a kernel Mosaic currently refuses to compile on hardware (it runs
    here in interpret mode only)."""
    from pagerank_tpu.ops import spmv

    rng = np.random.default_rng(7)
    n, e, chunk = 700, 6000, 16
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    pack = ell_lib.ell_pack(g)
    src, rb, rb0 = _sentinel_form(pack, chunk)

    z = np.zeros(pack.n_padded + 8, np.float32)
    z[: g.n] = rng.random(g.n).astype(np.float32)

    y_pallas = np.asarray(pallas_spmv.ell_contrib_pallas(
        jnp.asarray(z), jnp.asarray(src), jnp.asarray(rb),
        jnp.asarray(rb0), pack.num_blocks, chunk=chunk, gather=gather,
        interpret=True,
    ))
    y_ell = np.asarray(spmv.ell_contrib(
        jnp.asarray(z), jnp.asarray(src), jnp.asarray(rb),
        pack.num_blocks, gather_width=8, chunk_rows=None, group=1,
    ))
    np.testing.assert_allclose(y_pallas, y_ell, rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("ndev", [1, 2])
def test_engine_pallas_kernel_matches_oracle(ndev):
    # Full engine with kernel="pallas" (interpret mode on CPU) vs the
    # f64 oracle; also exercises the sharded per-device rb0 slicing.
    from pagerank_tpu import JaxTpuEngine, PageRankConfig, ReferenceCpuEngine

    rng = np.random.default_rng(21)
    n, e = 400, 3000
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    cfg = PageRankConfig(
        num_iters=8, kernel="pallas", dtype="float64", accum_dtype="float64",
        num_devices=ndev,
    )
    eng = JaxTpuEngine(cfg).build(g)
    assert eng._kernel.startswith("pallas")
    r_p = eng.run()
    r_cpu = ReferenceCpuEngine(cfg).build(g).run()
    np.testing.assert_allclose(r_p, r_cpu, rtol=0, atol=1e-12)


def test_engine_pallas_vmem_budget_refused():
    from pagerank_tpu import JaxTpuEngine, PageRankConfig

    rng = np.random.default_rng(2)
    n = 1 << 21  # 2M vertices * f64 > 12MB budget
    g = build_graph(rng.integers(0, n, 1000), rng.integers(0, n, 1000), n=n)
    cfg = PageRankConfig(kernel="pallas", dtype="float64", accum_dtype="float64",
                         num_devices=1)
    with pytest.raises(ValueError, match="VMEM"):
        JaxTpuEngine(cfg).build(g)


def test_pallas_block_boundary_accumulation():
    # A single dst block whose rows span many chunks: every chunk RMWs
    # the same output rows — the donated-zeros + accumulate path.
    n = 64  # one 128-block after padding
    e_per = 40
    src = np.repeat(np.arange(32), e_per)  # 32 sources
    dst = np.tile(np.arange(32), e_per)
    g = build_graph(src, dst, n=n, dedup=False)
    pack = ell_lib.ell_pack(g)
    chunk = 8
    s, rb, rb0 = _sentinel_form(pack, chunk)
    rng = np.random.default_rng(1)
    r = rng.random(n).astype(np.float32)
    inv = inv_out_degree(g.out_degree, dtype=np.float64)[pack.perm]
    z = np.zeros(pack.n_padded + 8, np.float32)
    z[: g.n] = r[pack.perm] * inv[: g.n]
    y = pallas_spmv.ell_contrib_pallas(
        jnp.asarray(z), jnp.asarray(s), jnp.asarray(rb), jnp.asarray(rb0),
        pack.num_blocks, chunk=chunk, gather="take", interpret=True,
    )
    got = np.empty(g.n, np.float64)
    got[pack.perm] = np.asarray(y)[: g.n]
    expected = to_csr_transpose(g) @ r.astype(np.float64)
    np.testing.assert_allclose(got, expected, rtol=2e-6, atol=2e-7)
