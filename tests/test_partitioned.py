"""Partition-centric SpMV restage (ISSUE 6): the windowed ell_contrib
mode against the numpy oracle and the plain op, the engine's partitioned
layout against the f64 CPU oracle and the plain engine on every build
path (host, device, sharded, fused, probed), the pallas probe-fallback
rebuild, the stage_call donation hardening, and the standing cost-model
gate (partitioned step must MODEL fewer HBM bytes per edge than the
plain step at a dense-cell geometry — the acceptance comparator when no
TPU is available)."""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pagerank_tpu import (JaxTpuEngine, PageRankConfig, ReferenceCpuEngine,
                          build_graph)
from pagerank_tpu.ops import LANES
from pagerank_tpu.ops import ell as ell_lib
from pagerank_tpu.ops import spmv


# -- op level ---------------------------------------------------------------


def _partitioned_fixture(n=1024, e=30000, psz=256, group=8, gw=8, chunk=8,
                         seed=0, words24=True):
    """Hand-assemble the partitioned form of a small graph exactly the
    way the engine does (partition-major rows, chunk-padded partitions,
    window-local words, chunk-local int16 pair ranks, (window, rank)
    bases) and return everything needed to run + verify it."""
    rng = np.random.default_rng(seed)
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    pack = ell_lib.ell_pack_striped(g, stripe_size=psz, group=group)
    K = pack.n_stripes
    nb = pack.num_blocks
    log2g = group.bit_length() - 1
    sent = np.int32(psz << log2g)
    win_rows = (psz + gw) // gw

    srcs, rks, ids_cat, counts, rows_tab = [], [], [], [], []
    pair_off = 0
    for p in range(K):
        ss = np.where(pack.weight[p] != 0, pack.src[p], sent)
        rk, ids_p, pc, _pref = ell_lib.dense_block_ranks(
            pack.row_block[p], nb
        )
        rows = ss.shape[0]
        pad = -(-max(rows, 1) // chunk) * chunk - rows
        ss = np.concatenate([ss, np.full((pad, LANES), sent, np.int32)])
        rk = np.concatenate(
            [rk, np.full(pad, max(0, pc - 1), np.int32)]
        ) + pair_off
        srcs.append(ss)
        rks.append(rk)
        ids_cat.append(ids_p)
        counts.append(pc)
        rows_tab.append(ss.shape[0])
        pair_off += pc
    src_cat = np.concatenate(srcs)
    ranks = np.concatenate(rks)
    nc = src_cat.shape[0] // chunk
    wb = np.repeat(
        np.arange(K, dtype=np.int32) * np.int32(win_rows),
        [r // chunk for r in rows_tab],
    )
    rb0 = ranks[::chunk].astype(np.int32)
    rb_loc = (ranks - np.repeat(rb0, chunk)).astype(np.int16)
    bases = np.stack([wb, rb0], axis=1)
    if words24:
        assert psz * group < (1 << 24)
        src_arr = spmv.pack_words24(src_cat, np)
    else:
        src_arr = src_cat
    return dict(
        g=g, pack=pack, K=K, nb=nb, psz=psz, gw=gw, group=group,
        chunk=chunk, win_rows=win_rows, src=src_arr, rb_loc=rb_loc,
        bases=bases, ids_cat=ids_cat, counts=counts,
        pairs_total=pair_off, nc=nc,
    )


def _partitioned_z(z_pad, K, psz, gw, dtype=np.float32):
    """The engine's partition-padded z layout: (K, psz) + gw zero lanes
    per partition, flattened."""
    z2 = np.asarray(z_pad, dtype).reshape(K, psz)
    return np.concatenate([z2, np.zeros((K, gw), dtype)], axis=1).reshape(-1)


def _expand_pairs(y_pairs, fx, dtype=np.float64):
    out = np.zeros((fx["nb"], LANES), dtype)
    off = 0
    for p in range(fx["K"]):
        cnt = fx["counts"][p]
        out[fx["ids_cat"][p]] += y_pairs[off:off + cnt]
        off += cnt
    return out.reshape(-1)


@pytest.mark.parametrize("words24", [True, False])
@pytest.mark.parametrize("group", [1, 8])
def test_ell_contrib_windowed_matches_plain_and_oracle(words24, group):
    fx = _partitioned_fixture(group=group, words24=words24)
    g, pack = fx["g"], fx["pack"]
    n_pad = pack.n_padded
    rng = np.random.default_rng(1)
    z = np.zeros(n_pad, np.float32)
    z[: g.n] = rng.random(g.n).astype(np.float32)

    zp = _partitioned_z(z, fx["K"], fx["psz"], fx["gw"])
    y = spmv.ell_contrib(
        jnp.asarray(zp), jnp.asarray(fx["src"]), jnp.asarray(fx["rb_loc"]),
        fx["nb"], gather_width=fx["gw"], chunk_rows=fx["chunk"],
        group=group, num_present=fx["pairs_total"],
        window_rows=fx["win_rows"], chunk_bases=jnp.asarray(fx["bases"]),
    )
    got = _expand_pairs(np.asarray(y).reshape(-1, LANES), fx)

    # Oracle over the SAME striped pack, in the op's SENTINEL
    # semantics: the op consumes PRE-SCALED z (weights are not
    # multiplied — they only mark inert slots, which point at the
    # zero sentinel), so y[d] = sum over LIVE slots of z_local[src].
    expect = np.zeros(n_pad)
    lg = group.bit_length() - 1
    for p in range(fx["K"]):
        lo = p * fx["psz"]
        zfull = np.zeros(fx["psz"] + 1)
        avail = min(fx["psz"], n_pad - lo)
        zfull[:avail] = z[lo: lo + avail].astype(np.float64)
        live = pack.weight[p] != 0
        src_p, rb_p = pack.src[p], pack.row_block[p]
        y2 = np.zeros((fx["nb"], LANES))
        if group == 1:
            v = np.where(live, zfull[src_p], 0.0)
            np.add.at(y2, rb_p, v)
        else:
            v = np.where(live, zfull[src_p >> lg], 0.0)
            pos = np.arange(LANES)
            lane = (pos[None, :] & ~(group - 1)) | (src_p & (group - 1))
            np.add.at(y2, (rb_p[:, None], lane), v)
        expect += y2.reshape(-1)
    np.testing.assert_allclose(got, expect, rtol=2e-6, atol=2e-7)


def test_ell_contrib_bf16_window_is_exact_selection():
    """The bf16-streamed table must equal the f32 path run on the
    bf16-QUANTIZED z exactly at the selection stage: the one-hot select
    is pure selection, so the only error is z's quantization."""
    fx = _partitioned_fixture(group=8)
    g = fx["g"]
    rng = np.random.default_rng(2)
    z = np.zeros(fx["pack"].n_padded, np.float32)
    z[: g.n] = rng.random(g.n).astype(np.float32)
    zp32 = _partitioned_z(z, fx["K"], fx["psz"], fx["gw"])
    zpb = jnp.asarray(zp32).astype(jnp.bfloat16)

    args = (jnp.asarray(fx["src"]), jnp.asarray(fx["rb_loc"]),
            fx["nb"])
    kw = dict(gather_width=fx["gw"], chunk_rows=fx["chunk"], group=8,
              num_present=fx["pairs_total"], window_rows=fx["win_rows"],
              chunk_bases=jnp.asarray(fx["bases"]),
              accum_dtype=jnp.float32)
    y_b = spmv.ell_contrib(zpb, *args, **kw)
    # f32 table holding the bf16-quantized values: selection being
    # exact, the two reductions see IDENTICAL per-slot values.
    y_q = spmv.ell_contrib(zpb.astype(jnp.float32), *args, **kw)
    np.testing.assert_array_equal(np.asarray(y_b), np.asarray(y_q))


def test_pack_words24_roundtrip():
    rng = np.random.default_rng(3)
    w = rng.integers(0, 1 << 24, (7, LANES)).astype(np.int32)
    packed = spmv.pack_words24(w, np)
    assert packed.dtype == np.int8 and packed.shape == (7, 3 * LANES)
    out = np.asarray(spmv.unpack_words24(jnp.asarray(packed)))
    np.testing.assert_array_equal(out, w)


# -- engine level -----------------------------------------------------------


def _graph(n=2000, e=60000, seed=5):
    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


@pytest.mark.parametrize("ndev", [1, 2])
def test_engine_partitioned_matches_oracle_and_plain(ndev):
    g = _graph()
    cfg = PageRankConfig(num_iters=10, partition_span=512,
                         num_devices=ndev).validate()
    eng = JaxTpuEngine(cfg).build(g)
    li = eng.layout_info()
    assert li["form"] == "partitioned" and li["partition_span"] == 512
    assert li["partitions"] == -(-eng._n_state // 512)
    r = eng.run_fast()

    cfg64 = PageRankConfig(num_iters=10, dtype="float64",
                           accum_dtype="float64")
    r_cpu = ReferenceCpuEngine(cfg64).build(g).run()
    assert np.abs(r - r_cpu).sum() / np.abs(r_cpu).sum() < 1e-5

    r_plain = JaxTpuEngine(
        PageRankConfig(num_iters=10, num_devices=ndev)
    ).build(g).run_fast()
    np.testing.assert_allclose(r, r_plain, rtol=1e-5, atol=1e-7)


def test_engine_partitioned_fused_forms_match_stepwise():
    g = _graph()
    cfg = PageRankConfig(num_iters=8, partition_span=512).validate()
    r_step = JaxTpuEngine(cfg).build(g).run_fast()
    r_fused = JaxTpuEngine(cfg).build(g).run_fused()
    np.testing.assert_array_equal(np.asarray(r_fused), np.asarray(r_step))
    e3 = JaxTpuEngine(cfg.replace(tol=1e-12)).build(g)
    r_tol = e3.run_fused_tol()
    np.testing.assert_array_equal(np.asarray(r_tol), np.asarray(r_step))


def test_engine_partitioned_device_build_matches_host():
    from pagerank_tpu.ops import device_build as db

    rng = np.random.default_rng(7)
    n, e = 1500, 40000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    cfg = PageRankConfig(num_iters=6, partition_span=512).validate()
    grp, stripe, part = db.plan_build(cfg, n, num_edges=e,
                                      partition_span=512)
    assert part == 512 and stripe == 512
    dg = db.build_ell_device(
        jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32),
        n=n, group=grp, stripe_size=stripe, with_weights=False,
    )
    r_dev = JaxTpuEngine(cfg).build_device(dg).run_fast()
    r_host = JaxTpuEngine(cfg).build(build_graph(src, dst, n=n)).run_fast()
    np.testing.assert_allclose(r_dev, r_host, rtol=1e-5, atol=1e-7)


def test_engine_partitioned_device_build_span_mismatch_raises():
    from pagerank_tpu.ops import device_build as db

    rng = np.random.default_rng(8)
    src = jnp.asarray(rng.integers(0, 512, 4096), jnp.int32)
    dst = jnp.asarray(rng.integers(0, 512, 4096), jnp.int32)
    dg = db.build_ell_device(src, dst, n=512, with_weights=False)  # 1 stripe
    cfg = PageRankConfig(num_iters=2, partition_span=128).validate()
    with pytest.raises(ValueError, match="partition_span"):
        JaxTpuEngine(cfg).build_device(dg)


def test_engine_partitioned_probe_zero_is_bit_identical():
    """ISSUE 6 acceptance: --probe-every 0 on the partitioned form is
    bit-identical to a probed run's ranks, and the unprobed path makes
    ZERO probe calls (the PTC007 behavioral half; the structural half
    runs in the contract sweep)."""
    g = _graph()
    cfg0 = PageRankConfig(num_iters=6, partition_span=512).validate()
    eng_plain = JaxTpuEngine(cfg0).build(g)
    booby = {"calls": 0}
    orig = eng_plain._get_probe_fn

    def trap(k):
        booby["calls"] += 1
        return orig(k)

    eng_plain._get_probe_fn = trap
    r_plain = eng_plain.run()

    cfg_p = PageRankConfig(num_iters=6, partition_span=512,
                           probe_every=2).validate()
    eng_probed = JaxTpuEngine(cfg_p).build(g)
    from pagerank_tpu.obs.probes import ConvergenceProbes

    probes = ConvergenceProbes(2, topk=8)
    r_probed = eng_probed.run(probes=probes)
    assert len(probes.history) == 3
    assert booby["calls"] == 0
    np.testing.assert_array_equal(np.asarray(r_plain),
                                  np.asarray(r_probed))


def test_engine_bf16_stream_error_bounded_by_oracle():
    g = _graph()
    cfg = PageRankConfig(num_iters=10, partition_span=512,
                         stream_dtype="bfloat16").validate()
    r_b = JaxTpuEngine(cfg).build(g).run_fast()
    r_cpu = ReferenceCpuEngine(
        PageRankConfig(num_iters=10, dtype="float64",
                       accum_dtype="float64")
    ).build(g).run()
    norm = np.abs(r_b - r_cpu).sum() / np.abs(r_cpu).sum()
    # bf16 stream: ~2^-9 relative z quantization per gather; the f32
    # leg lands ~1e-7 here. Bound the leg well inside quantization
    # grade and assert it is a REAL bf16 run (worse than f32 rounding).
    assert 1e-7 < norm < 5e-3, norm


def test_partition_span_rule():
    rule = JaxTpuEngine.partition_span
    # Dense bench-class geometry: raw scale-23 ef-16 counts resolve a
    # 2M span (cells exactly at the threshold).
    assert rule(1 << 23, 16 << 23) == 1 << 21
    # The coarsest valid layout — exactly two partitions — is reachable
    # (the r6 review caught the loop skipping the n_padded/2 check).
    assert rule(1 << 16, 64 << 16) == 1 << 15
    # The rule respects the partition-count cap: an ultra-dense graph
    # may not auto-resolve a span finer than n_padded/MAX_PARTITIONS
    # (it would trip the setup's own explicit-span guard).
    span = rule(1 << 24, 1 << 35)
    assert span and (1 << 24) // span <= JaxTpuEngine.MAX_PARTITIONS
    # Too small / too sparse: off.
    assert rule(1 << 12, 16 << 12) == 0
    assert rule(1 << 23, 1 << 23) == 0
    assert rule(0, 0) == 0 and rule(1 << 23, None) == 0


def test_config_partition_validation():
    with pytest.raises(ValueError, match="multiple of 128"):
        PageRankConfig(partition_span=100).validate()
    with pytest.raises(ValueError, match="32-bit"):
        PageRankConfig(partition_span=256, dtype="float64",
                       accum_dtype="float64").validate()
    with pytest.raises(ValueError, match="vertex_sharded"):
        PageRankConfig(partition_span=256, vertex_sharded=True).validate()
    with pytest.raises(ValueError, match="ell or pallas kernel"):
        PageRankConfig(partition_span=256, kernel="coo").validate()
    with pytest.raises(ValueError, match="stream_dtype"):
        PageRankConfig(stream_dtype="float16",
                       partition_span=256).validate()
    # stream without the partitioned layout would be silently ignored;
    # validate refuses instead (r6 review).
    with pytest.raises(ValueError, match="partition_span"):
        PageRankConfig(stream_dtype="bfloat16").validate()


def test_partition_count_cap_and_span_rounding():
    # Undersized explicit span: refused loudly instead of exploding
    # padding/compile (r6 review).
    g = _graph(n=40000, e=80000)
    cfg = PageRankConfig(num_iters=1, partition_span=128).validate()
    with pytest.raises(ValueError, match="partitions"):
        JaxTpuEngine(cfg).build(g)
    # plan_build rounds a non-multiple-of-128 explicit span instead of
    # handing the config an invalid value (r6 review: the CLI/bench
    # would otherwise crash at validate after the build).
    from pagerank_tpu.ops import device_build as db

    cfg2 = PageRankConfig(num_iters=1).validate()
    _g, stripe, part = db.plan_build(cfg2, 4096, num_edges=1 << 16,
                                     partition_span=200)
    assert part == stripe == 128


def test_layout_info_attributes_dispatch_forms():
    """layout_info()'s form must say what ACTUALLY dispatches (r6
    review: multi-dispatch builds reported 'step')."""
    from pagerank_tpu.analysis.contracts import _classes

    g = _graph(n=1200, e=20000)
    _Eng, _Tiny, Scan = _classes()
    ms = Scan(PageRankConfig(num_iters=1, num_devices=1)).build(g)
    assert ms._ms_stripe is not None
    assert ms.layout_info()["form"] == "multi_dispatch"
    coo = JaxTpuEngine(
        PageRankConfig(num_iters=1, kernel="coo", num_devices=1)
    ).build(g)
    li = coo.layout_info()
    assert li["form"] == "coo" and li["kernel"] == "coo"
    vs = JaxTpuEngine(
        PageRankConfig(num_iters=1, vertex_sharded=True, num_devices=2)
    ).build(g)
    assert vs.layout_info()["form"] == "vertex_sharded"
    vsb = JaxTpuEngine(
        PageRankConfig(num_iters=1, vertex_sharded=True, vs_bounded=True,
                       num_devices=2)
    ).build(g)
    assert vsb.layout_info()["form"] == "vs_bounded"


# -- cost-model gate --------------------------------------------------------


def test_partitioned_step_models_fewer_bytes_per_edge():
    """THE acceptance comparator on a TPU-less substrate (ISSUE 6):
    at a dense-cell geometry, the partitioned step form's XLA cost
    model must show FEWER HBM bytes per edge than the plain step form
    — corroborating (not replacing) the wall-clock measurement the
    bench legs take on real hardware. Dense cells matter: at sparse
    cells the ELL row-padding floor inverts the comparison (the
    partition_span auto rule exists to refuse that regime)."""
    from pagerank_tpu.obs import costs as obs_costs

    rng = np.random.default_rng(0)
    scale, ef, span = 16, 128, 16384
    n = 1 << scale
    g = build_graph(rng.integers(0, n, ef << scale),
                    rng.integers(0, n, ef << scale), n=n)

    def step_bpe(cfg):
        # num_devices=1: the bench/acceptance comparison is single-chip
        # (the conftest's fake-8 mesh would instead measure the
        # 8-way-sharded pad geometry).
        eng = JaxTpuEngine(cfg.validate()).build(g)
        obs_costs.reset()
        eng.cost_reports()
        rep = obs_costs.get_report("step")
        assert rep is not None and rep.bytes_per_edge is not None
        return rep.bytes_per_edge

    bpe_plain = step_bpe(PageRankConfig(num_iters=2, num_devices=1))
    bpe_part = step_bpe(PageRankConfig(num_iters=2, num_devices=1,
                                       partition_span=span))
    bpe_bf16 = step_bpe(PageRankConfig(num_iters=2, num_devices=1,
                                       partition_span=span,
                                       stream_dtype="bfloat16"))
    obs_costs.reset()
    assert bpe_part < bpe_plain, (bpe_part, bpe_plain)
    assert bpe_bf16 < bpe_plain, (bpe_bf16, bpe_plain)


def test_autotune_partitioned_branch_times_candidates(monkeypatch):
    """The partitioned autotune branch is TPU-gated in production, so
    force it on CPU (backend monkeypatch + big-table sizes) and prove
    it actually lowers, times, and picks a candidate — the r6 review
    caught a positional/keyword collision here that made every
    candidate raise into the bare except and silently degrade to the
    smallest untimed chunk."""
    import jax as jax_mod

    fx = _partitioned_fixture(n=2048, e=60000, psz=512, group=8, gw=8,
                              chunk=256)
    rows = fx["src"].shape[0]
    ranks_glob = jnp.asarray(
        np.asarray(fx["bases"][:, 1]).repeat(fx["chunk"])[:rows]
        + np.asarray(fx["rb_loc"], np.int32)
    )
    rows_per_part = [r for r in
                     np.bincount(fx["bases"][:, 0] // fx["win_rows"],
                                 minlength=fx["K"]) * fx["chunk"]]

    def bases_for(c):
        rb0 = ranks_glob[::c]
        rb_loc = (ranks_glob - jnp.repeat(
            rb0, c, total_repeat_length=rows)).astype(jnp.int16)
        wb = np.repeat(
            np.arange(fx["K"], dtype=np.int32) * np.int32(fx["win_rows"]),
            [r // c for r in rows_per_part],
        )
        return rb_loc, jnp.stack(
            [jnp.asarray(wb), rb0.astype(jnp.int32)], axis=1)

    cfg = PageRankConfig(num_iters=1, num_devices=1).validate()
    eng = JaxTpuEngine(cfg)
    eng._mesh = None  # unused by the impl's part branch
    monkeypatch.setattr(jax_mod, "default_backend", lambda: "tpu")
    # tuning_put fires ONLY when at least one candidate was actually
    # timed — the collision bug fell through with nothing compiled and
    # never wrote the tuning record.
    from pagerank_tpu.utils import compile_cache

    timed = {}
    monkeypatch.setattr(compile_cache, "tuning_put",
                        lambda k, v: timed.update({k: v}))
    eng.build_timings = {}
    table_len = fx["K"] * (fx["psz"] + fx["gw"])
    chosen = eng._autotune_chunk(
        [64, 256], [rows], 1 << 23, 4, fx["gw"], 8, False,
        jnp.float32, [fx["pairs_total"]], 1,
        part=dict(window_rows=fx["win_rows"], table_len=table_len,
                  table_dt=jnp.float32, src_dev=jnp.asarray(fx["src"]),
                  bases_for=bases_for, pairs=fx["pairs_total"]),
    )
    assert chosen in (64, 256)
    assert timed and list(timed.values()) == [chosen]


# -- pallas probe fallback --------------------------------------------------


def test_pallas_probe_failure_falls_back_to_native_layout(monkeypatch):
    """When BOTH Mosaic gather strategies fail to lower, the engine
    must REBUILD with the native ell layout (grouped lanes + slab
    scan) — not run the XLA path on the pallas-shaped group-1 non-slab
    arrays — log the downgrade, and record the resolved kernel."""
    from pagerank_tpu.ops import pallas_spmv

    def boom(*a, **k):
        raise NotImplementedError("Only 2D gather is supported")

    monkeypatch.setattr(pallas_spmv, "ell_contrib_pallas", boom)
    g = _graph(n=800, e=8000)
    cfg = PageRankConfig(num_iters=6, kernel="pallas").validate()
    eng = JaxTpuEngine(cfg).build(g)
    li = eng.layout_info()
    assert li["kernel"] == "ell"
    assert li["kernel_requested"] == "pallas"
    # Native layout: the auto lane group (not pallas' forced group 1)
    # and the slab-scan dense-rank form.
    assert li["group"] == cfg.effective_lane_group(False)
    r = eng.run_fast()
    r_native = JaxTpuEngine(
        PageRankConfig(num_iters=6, kernel="ell")
    ).build(g).run_fast()
    np.testing.assert_array_equal(np.asarray(r), np.asarray(r_native))


def test_pallas_probe_failure_device_build(monkeypatch):
    from pagerank_tpu.ops import device_build as db
    from pagerank_tpu.ops import pallas_spmv

    def boom(*a, **k):
        raise ValueError("Shape mismatch in input, indices and output")

    monkeypatch.setattr(pallas_spmv, "ell_contrib_pallas", boom)
    rng = np.random.default_rng(9)
    src = rng.integers(0, 512, 4096)
    dst = rng.integers(0, 512, 4096)
    dg = db.build_ell_device(
        jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32),
        n=512, with_weights=False,
    )
    cfg = PageRankConfig(num_iters=4, kernel="pallas").validate()
    eng = JaxTpuEngine(cfg).build_device(dg)
    assert eng.layout_info()["kernel"] == "ell"
    assert eng.layout_info()["kernel_requested"] == "pallas"
    r = eng.run_fast()
    r_host = JaxTpuEngine(
        PageRankConfig(num_iters=4, kernel="ell")
    ).build(build_graph(src, dst, n=512)).run_fast()
    np.testing.assert_allclose(r, r_host, rtol=1e-6, atol=1e-7)


# -- stage_call donation hardening -----------------------------------------


def test_stage_call_drops_unconsumable_donation():
    """A stage whose donated input can never alias (no matching output
    aval) must dispatch WITHOUT the donation — correct result, no
    'donated buffers were not usable' warning escaping (the r1-r5
    bench-tail residual), and the downgrade logged."""
    from pagerank_tpu.utils import compile_cache

    compile_cache.clear_stage_cache()

    def bad_stage(x):  # int32[64] in, f32[8] out: can never alias
        return jnp.zeros(8, jnp.float32) + x.sum()

    x = jnp.arange(64, dtype=jnp.int32)
    with warnings.catch_warnings(record=True) as wlog:
        warnings.simplefilter("always")
        out = compile_cache.stage_call(
            "test_bad_donation", bad_stage, (x,), donate_argnums=(0,)
        )
    assert float(np.asarray(out)[0]) == float(np.arange(64).sum())
    assert not any(
        "donated buffers were not usable" in str(w.message) for w in wlog
    )
    # x must NOT have been donated (still readable).
    assert int(jnp.sum(x)) == int(np.arange(64).sum())
    compile_cache.clear_stage_cache()


def test_usable_donations_matching():
    from pagerank_tpu.utils.compile_cache import usable_donations

    S = jax.ShapeDtypeStruct

    def fn(a, b, c):
        return a + 1, c.astype(jnp.float32)

    args = (S((16,), jnp.int32), S((16,), jnp.int32), S((4,), jnp.int32))
    # a matches output 0; b has no second int32[16] output; c's only
    # shape-mate is f32 (dtype mismatch).
    assert usable_donations(fn, args, (0, 1, 2)) == (0,)


def test_device_build_emits_no_donation_warning():
    """End to end: no device build layout may leak the donation
    warning (the BENCH_r05 / MULTICHIP_r05 tail residual)."""
    from pagerank_tpu.ops import device_build as db

    rng = np.random.default_rng(11)
    for kw in (dict(), dict(group=4, stripe_size=128, with_weights=False),
               dict(stripe_size=128, with_weights=False)):
        src = jnp.asarray(rng.integers(0, 256, 4096), jnp.int32)
        dst = jnp.asarray(rng.integers(0, 256, 4096), jnp.int32)
        with warnings.catch_warnings(record=True) as wlog:
            warnings.simplefilter("always")
            db.build_ell_device(src, dst, n=256, **kw)
        assert not any(
            "donated buffers were not usable" in str(w.message)
            for w in wlog
        ), kw
