"""Observability layer tests (ISSUE 4; docs/OBSERVABILITY.md): span
nesting/ordering invariants, Chrome trace-event schema, the no-op
tracer's zero-cost contract, registry snapshot round-trip, the CLI
flight recorder's schema stability, strict-JSON metrics output, and the
profiler session's stop-on-failure path."""

import json
import threading

import numpy as np
import pytest

from pagerank_tpu import obs
from pagerank_tpu.obs import trace as obs_trace
from pagerank_tpu.obs.metrics import MetricsRegistry
from pagerank_tpu.obs.report import REPORT_KEYS


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Process-global tracer/registry must never leak between tests."""
    obs.disable_tracing()
    obs.get_registry().reset()
    yield
    obs.disable_tracing()
    obs.get_registry().reset()


def _strict_loads(s):
    """json.loads that REJECTS NaN/Infinity — what a spec-compliant
    JSONL consumer does (the regression the inf->null fix pins)."""

    def _no_const(name):
        raise ValueError(f"non-spec JSON constant {name!r}")

    return json.loads(s, parse_constant=_no_const)


# -- span tracing -----------------------------------------------------------


def test_span_nesting_and_ordering():
    tr = obs_trace.Tracer()
    with tr.span("solve/run", engine="t") as outer:
        with tr.span("solve/step", iteration=0) as s0:
            pass
        with tr.span("solve/step", iteration=1) as s1:
            pass
    spans = tr.spans()
    assert [s.name for s in spans] == [
        "solve/step", "solve/step", "solve/run"
    ]  # children finish (and record) before the parent
    assert s0.parent_id == outer.span_id
    assert s1.parent_id == outer.span_id
    assert outer.parent_id is None
    # Containment: children start at/after the parent and end at/before
    # it; siblings are ordered.
    assert outer.start <= s0.start and s0.end <= outer.end
    assert outer.start <= s1.start and s1.end <= outer.end
    assert s0.end <= s1.start
    assert all(s.duration >= 0 for s in spans)
    assert s0.attrs["iteration"] == 0 and s1.attrs["iteration"] == 1


def test_span_records_error_attribute():
    tr = obs_trace.Tracer()
    with pytest.raises(ValueError):
        with tr.span("snapshot/save"):
            raise ValueError("boom")
    (sp,) = tr.spans()
    assert sp.attrs["error"] == "ValueError"


def test_span_threads_do_not_cross_link():
    """A worker thread's spans must not parent under the main thread's
    open span (the AsyncRankWriter records concurrently with the solve
    loop)."""
    tr = obs_trace.Tracer()
    seen = {}

    def worker():
        with tr.span("writer/queue_wait") as sp:
            seen["span"] = sp

    with tr.span("solve/run"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen["span"].parent_id is None
    assert seen["span"].tid != threading.get_ident()


def test_summary_and_timings_view():
    tr = obs_trace.Tracer()
    with tr.span("build/sort"):
        pass
    with tr.span("build/sort"):
        pass
    with tr.span("build/scatter"):
        pass
    summ = tr.summary()
    assert summ["build/sort"]["count"] == 2
    assert summ["build/sort"]["total_s"] == pytest.approx(
        summ["build/sort"]["mean_s"] * 2
    )
    view = tr.timings_view("build/")
    assert set(view) == {"sort_s", "scatter_s"}


def test_chrome_trace_event_schema(tmp_path):
    tr = obs_trace.Tracer()
    with tr.span("a/b", k=1):
        with tr.span("a/c"):
            pass
    tr.add_event("log/info", message="hello")
    path = str(tmp_path / "trace.json")
    tr.export(path)
    doc = _strict_loads(open(path).read())
    evs = doc["traceEvents"]
    assert len(evs) == 3
    for ev in evs:
        # The trace-event schema fields Perfetto/chrome://tracing need.
        assert isinstance(ev["name"], str)
        assert ev["ph"] in ("X", "i")
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        else:
            assert ev["args"]["message"] == "hello"


def test_chrome_counter_track_schema(tmp_path):
    """Per-device counter tracks (ISSUE 10): tracked add_counter
    samples render as Chrome ``ph:"C"`` events on their OWN pid lane
    with a ``process_name`` metadata event naming the lane, so
    Perfetto shows one HBM track per device; untracked counters ride
    the process pid. The NullTracer's add_counter is a no-op."""
    tr = obs.enable_tracing()
    tr.add_counter("device.0.hbm", {"bytes_in_use": 5},
                   track=1 << 20, track_label="device tpu:0 (TPU v4)")
    tr.add_counter("device.0.hbm", {"bytes_in_use": 9},
                   track=1 << 20, track_label="device tpu:0 (TPU v4)")
    tr.add_counter("loose.counter", {"v": 1})
    events = tr.chrome_events()
    tracked = [e for e in events
               if e["ph"] == "C" and e["name"] == "device.0.hbm"]
    assert [e["args"]["bytes_in_use"] for e in tracked] == [5, 9]
    assert all(e["pid"] == 1 << 20 for e in tracked)
    assert tracked[0]["ts"] <= tracked[1]["ts"]
    metas = [e for e in events if e["ph"] == "M"]
    assert len(metas) == 1  # one label per track, not per sample
    assert metas[0]["args"]["name"] == "device tpu:0 (TPU v4)"
    loose = [e for e in events if e["ph"] == "C"
             and e["name"] == "loose.counter"]
    import os

    assert loose[0]["pid"] == os.getpid()
    # JSONL export carries the counters as strict-JSON lines.
    path = str(tmp_path / "c.jsonl")
    tr.export(path)
    counters = [_strict_loads(l) for l in open(path)
                if _strict_loads(l).get("type") == "counter"]
    assert len(counters) == 3
    # Disabled tracing: add_counter is a silent no-op.
    obs.disable_tracing()
    obs_trace.get_tracer().add_counter("x", {"v": 1})
    assert obs_trace.get_tracer().counters() == []


def test_jsonl_trace_export_is_strict(tmp_path):
    tr = obs_trace.Tracer()
    with tr.span("a/b"):
        pass
    tr.add_event("retry/backoff", delay_s=0.5)
    path = str(tmp_path / "trace.jsonl")
    tr.export(path)  # .jsonl extension dispatches the JSONL exporter
    lines = [_strict_loads(l) for l in open(path)]
    assert lines[0]["type"] == "trace_header"
    kinds = {l["type"] for l in lines[1:]}
    assert kinds == {"span", "event"}


def test_noop_tracer_hot_path():
    """With observability disabled the solve hot path makes ZERO tracer
    calls per iteration (the acceptance criterion): a booby-trapped
    disabled tracer runs a full engine.run without tripping, and the
    NullTracer's span() allocates nothing (one shared cm)."""
    from pagerank_tpu import PageRankConfig, ReferenceCpuEngine, build_graph

    class BombTracer:
        enabled = False

        def span(self, *a, **k):  # pragma: no cover - the trap
            raise AssertionError("tracer touched on the disabled hot path")

        add_span = add_event = span

    assert obs_trace.get_tracer() is obs_trace.NULL_TRACER
    # NullTracer.span() is allocation-free: the SAME object every call.
    null = obs_trace.NULL_TRACER
    assert null.span("x") is null.span("y", a=1)
    obs_trace._TRACER = BombTracer()
    try:
        rng = np.random.default_rng(0)
        g = build_graph(rng.integers(0, 50, 300),
                        rng.integers(0, 50, 300), n=50)
        eng = ReferenceCpuEngine(PageRankConfig(num_iters=5)).build(g)
        eng.run()  # would raise if any per-iteration tracer call fired
        assert eng.iteration == 5
    finally:
        obs_trace._TRACER = obs_trace.NULL_TRACER


def test_enabled_tracer_records_solve_steps():
    from pagerank_tpu import PageRankConfig, ReferenceCpuEngine, build_graph

    tr = obs.enable_tracing()
    rng = np.random.default_rng(0)
    g = build_graph(rng.integers(0, 50, 300), rng.integers(0, 50, 300),
                    n=50)
    ReferenceCpuEngine(PageRankConfig(num_iters=4)).build(g).run()
    steps = [s for s in tr.spans() if s.name == "solve/step"]
    assert [s.attrs["iteration"] for s in steps] == [0, 1, 2, 3]


# -- metrics registry -------------------------------------------------------


def test_registry_snapshot_round_trip():
    reg = MetricsRegistry()
    reg.counter("s3.request.retries").inc(3)
    reg.gauge("engine.num_chips").set(8)
    h = reg.histogram("snapshot.save_bytes")
    h.record(100)
    h.record(5000)
    snap = reg.snapshot()
    # Round trip through strict JSON: identical structure and values.
    assert _strict_loads(json.dumps(snap)) == snap
    assert snap["counters"]["s3.request.retries"] == 3
    assert snap["gauges"]["engine.num_chips"] == 8
    hs = snap["histograms"]["snapshot.save_bytes"]
    assert hs["count"] == 2 and hs["min"] == 100 and hs["max"] == 5000
    assert sum(hs["buckets"].values()) == 2
    table = reg.render_table()
    assert "s3.request.retries" in table and "counter" in table


def test_registry_type_conflict_and_get_or_create():
    reg = MetricsRegistry()
    c = reg.counter("x")
    assert reg.counter("x") is c
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_sink_guard_registers_central_counters():
    from pagerank_tpu.utils.retry import RetryPolicy
    from pagerank_tpu.utils.snapshot import SinkGuard

    guard = SinkGuard(
        retry_policy=RetryPolicy(max_attempts=3, base_delay=0.0,
                                 sleep=lambda s: None, seed=0),
        on_failure="warn_and_drop",
    )
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise OSError("transient")

    assert guard(0, flaky) is True
    with pytest.warns(RuntimeWarning):
        assert guard(1, lambda: (_ for _ in ()).throw(OSError("x"))) is False
    snap = obs.get_registry().snapshot()
    assert snap["counters"]["sink.write_retries"] == guard.retries
    assert snap["counters"]["sink.dead_letters"] == 1


def test_engine_health_counters_register():
    """A NaN-poisoned run increments the central health/rollback
    counters alongside engine.health (the scattered counter it
    mirrors)."""
    from pagerank_tpu import PageRankConfig, ReferenceCpuEngine, build_graph
    from pagerank_tpu.engine import SolverHealthError

    rng = np.random.default_rng(1)
    g = build_graph(rng.integers(0, 30, 200), rng.integers(0, 30, 200),
                    n=30)
    eng = ReferenceCpuEngine(PageRankConfig(num_iters=6)).build(g)
    orig = eng.step

    def bad_step():
        info = orig()
        return {k: float("nan") for k in info}

    eng.step = bad_step
    with pytest.raises(SolverHealthError):
        eng.run()
    snap = obs.get_registry().snapshot()
    assert snap["counters"]["engine.health_check_failures"] >= 1
    assert "engine.rollbacks" not in snap["counters"]  # nothing to roll to


# -- strict-JSON metrics logger (satellite 1) -------------------------------


def test_metrics_jsonl_is_strict_json(tmp_path):
    """iters_per_sec/edges_per_sec_per_chip must be null (not bare
    Infinity) when dt == 0 — json.dumps would otherwise emit non-spec
    JSON that strict JSONL consumers reject."""
    import io

    from pagerank_tpu.utils.metrics import MetricsLogger

    path = str(tmp_path / "m.jsonl")
    m = MetricsLogger(num_edges=10, jsonl_path=path, stream=io.StringIO())
    m.record(0, {"l1_delta": 0.5}, dt=0.0)  # the degenerate-clock case
    m.record(1, {"l1_delta": 0.25}, dt=0.01)
    # NaN step info (a diverging solve under --no-health-checks) is the
    # same defect class: null, never a bare NaN token.
    m.record(2, {"l1_delta": float("nan"),
                 "dangling_mass": float("inf")}, dt=0.01)
    m.close()
    recs = [_strict_loads(l) for l in open(path)]
    assert recs[0]["iters_per_sec"] is None
    assert recs[0]["edges_per_sec_per_chip"] is None
    assert recs[1]["iters_per_sec"] == pytest.approx(100.0)
    assert recs[2]["l1_delta"] is None
    assert recs[2]["dangling_mass"] is None


# -- profiler session (satellite 2) -----------------------------------------


class _FakeProfiler:
    def __init__(self, fail_stop=False):
        self.calls = []
        self.fail_stop = fail_stop

    def start_trace(self, d):
        self.calls.append(("start", d))

    def stop_trace(self):
        self.calls.append(("stop",))
        if self.fail_stop:
            raise RuntimeError("stop failed")


def test_profiler_session_stops_on_failure(tmp_path, monkeypatch):
    import jax

    fake = _FakeProfiler()
    monkeypatch.setattr(jax, "profiler", fake)
    tr = obs.enable_tracing()
    with pytest.raises(ValueError, match="mid-run"):
        with obs.profiler_session(str(tmp_path / "prof")):
            raise ValueError("mid-run failure")
    # The profiler was stopped despite the failure, and the profile
    # span records both the directory and the error.
    assert fake.calls == [("start", str(tmp_path / "prof")), ("stop",)]
    (sp,) = [s for s in tr.spans() if s.name == "profile"]
    assert sp.attrs["dir"] == str(tmp_path / "prof")
    assert sp.attrs["error"] == "ValueError"


def test_profiler_session_stop_failure_never_masks_body_error(
    tmp_path, monkeypatch
):
    import jax

    fake = _FakeProfiler(fail_stop=True)
    monkeypatch.setattr(jax, "profiler", fake)
    with pytest.raises(ValueError, match="primary"):
        with obs.profiler_session(str(tmp_path / "p")):
            raise ValueError("primary")
    assert ("stop",) in fake.calls


def test_profiler_session_noop_without_dir():
    with obs.profiler_session(None) as active:
        assert active is False


# -- flight recorder --------------------------------------------------------


def test_run_report_build_and_diff():
    tr = obs.enable_tracing()
    with tr.span("solve/step"):
        pass
    obs.get_registry().counter("s3.request.retries").inc(2)
    a = obs.build_run_report(
        config={"num_iters": 3},
        tracer=tr,
        registry=obs.get_registry(),
        history=[{"iter": 0, "iters_per_sec": float("inf")}],
        summary={"iters": 3, "edges_per_sec_per_chip": 1e6},
        robustness={"rollbacks": 0},
    )
    # Strict JSON end to end — the inf in history is sanitized to null.
    a = _strict_loads(json.dumps(a))
    assert a["iterations"][0]["iters_per_sec"] is None
    for k in REPORT_KEYS:
        assert k in a
    b = json.loads(json.dumps(a))
    b["summary"]["edges_per_sec_per_chip"] = 2e6
    b["environment"]["jaxlib_version"] = "9.9.9"
    out = obs.diff_reports(a, b)
    assert "environment DIFFERS" in out
    assert "jaxlib_version" in out
    assert "edges_per_sec_per_chip" in out and "+100.0%" in out
    rendered = obs.render_report(a)
    assert "solve/step" in rendered and "s3.request.retries" in rendered


def test_cli_run_report_schema(tmp_path):
    """The acceptance-criterion CLI contract: one flag pair produces a
    complete, schema-stable run_report.json and a loadable Chrome
    trace."""
    from pagerank_tpu.cli import main

    report_path = str(tmp_path / "run_report.json")
    trace_path = str(tmp_path / "trace.json")
    rc = main([
        "--synthetic", "uniform:300:2000", "--engine", "cpu",
        "--iters", "4", "--log-every", "0",
        "--trace", trace_path, "--run-report", report_path,
    ])
    assert rc == 0
    report = _strict_loads(open(report_path).read())
    assert report["schema_version"] == 1
    for k in REPORT_KEYS:
        assert k in report, f"run report missing {k!r}"
    env = report["environment"]
    for k in ("jax_version", "jaxlib_version", "backend", "device_kind",
              "device_count", "process_count", "x64", "git_rev"):
        assert k in env, f"environment fingerprint missing {k!r}"
    assert report["config"]["num_iters"] == 4
    assert len(report["iterations"]) == 4
    assert report["summary"]["iters"] == 4
    assert report["graph"]["n"] == 300
    assert {"rollbacks", "write_retries", "dropped_writes",
            "s3_request_retries"} <= set(report["robustness"])
    # Span summary covers ingest and solve at minimum.
    assert "ingest/load" in report["spans"]
    assert "solve/step" in report["spans"]
    assert report["spans"]["solve/step"]["count"] == 4
    # The Chrome trace parses and carries the same phases.
    doc = _strict_loads(open(trace_path).read())
    names = {e["name"] for e in doc["traceEvents"]}
    assert "solve/step" in names and "ingest/load" in names
    # The CLI tore the global tracer back down on exit.
    assert obs_trace.get_tracer() is obs_trace.NULL_TRACER


def test_cli_jax_traced_run_records_engine_and_snapshot_spans(tmp_path):
    """A jax-engine traced run with snapshots exercises the deeper
    instrumentation: engine/build, snapshot/save, and the async
    writer's queue-wait spans all land in one trace."""
    from pagerank_tpu.cli import main

    report_path = str(tmp_path / "r.json")
    rc = main([
        "--synthetic", "uniform:256:1500", "--engine", "jax",
        "--iters", "3", "--log-every", "0",
        "--snapshot-dir", str(tmp_path / "snaps"),
        "--run-report", report_path,
    ])
    assert rc == 0
    report = _strict_loads(open(report_path).read())
    spans = report["spans"]
    assert "engine/build" in spans
    assert "snapshot/save" in spans and spans["snapshot/save"]["count"] == 3
    assert "writer/queue_wait" in spans
    counters = report["metrics"]["counters"]
    assert counters["snapshot.bytes_written"] > 0
    hist = report["metrics"]["histograms"]["snapshot.save_bytes"]
    assert hist["count"] == 3


def test_device_build_stage_spans_under_tracing():
    """Tracing a device build yields the per-stage build/ spans, and
    the timings dict stays a faithful view of the same fences."""
    jnp = pytest.importorskip("jax.numpy")
    from pagerank_tpu.ops.device_build import build_ell_device

    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.integers(0, 256, 2000), jnp.int32)
    dst = jnp.asarray(rng.integers(0, 256, 2000), jnp.int32)
    tr = obs.enable_tracing()
    dg = build_ell_device(src, dst, n=256, with_weights=False)
    assert dg.num_edges > 0
    view = tr.timings_view("build/")
    for key in ("relabel_s", "sort_s", "slots_s", "scatter_s"):
        assert key in view and view[key] >= 0.0


def test_cli_failure_path_still_writes_artifacts(tmp_path, monkeypatch):
    """A failing run must still produce its trace and (failure-marked)
    run report — the postmortem case the flight recorder exists for —
    and must tear the global tracer down."""
    from pagerank_tpu.cli import main
    from pagerank_tpu.engine import SolverHealthError
    from pagerank_tpu.engines.cpu import ReferenceCpuEngine

    orig = ReferenceCpuEngine.step

    def poisoned(self):
        info = orig(self)
        if self.iteration >= 2:
            return {k: float("nan") for k in info}
        return info

    monkeypatch.setattr(ReferenceCpuEngine, "step", poisoned)
    report_path = str(tmp_path / "r.json")
    trace_path = str(tmp_path / "t.json")
    with pytest.raises(SolverHealthError):
        main([
            "--synthetic", "uniform:200:1000", "--engine", "cpu",
            "--iters", "6", "--log-every", "0",
            "--trace", trace_path, "--run-report", report_path,
        ])
    report = _strict_loads(open(report_path).read())
    assert report["failed"] is True
    assert "SolverHealthError" in report["error"]
    assert report["spans"]["solve/step"]["count"] >= 2  # healthy steps
    assert report["metrics"]["counters"][
        "engine.health_check_failures"] >= 1
    # OOM forensics (ISSUE 10): the FAILURE-marked report still
    # carries the device-plane section with a teardown-time sample.
    devices = report["devices"]
    assert devices["samples"] >= 1
    assert devices["last"] and devices["last"][0]["id"] == 0
    doc = _strict_loads(open(trace_path).read())
    assert doc["traceEvents"]
    assert obs_trace.get_tracer() is obs_trace.NULL_TRACER


def test_cli_early_failure_writes_partial_report(tmp_path):
    """A run that dies BEFORE the solve (here: ingest of a missing
    input) still exports its artifacts — sections that never came to
    exist are null, the failure is marked."""
    from pagerank_tpu.cli import main

    report_path = str(tmp_path / "r.json")
    trace_path = str(tmp_path / "t.json")
    with pytest.raises(FileNotFoundError):
        main([
            "--input", str(tmp_path / "missing.txt"), "--engine", "cpu",
            "--log-every", "0",
            "--trace", trace_path, "--run-report", report_path,
        ])
    report = _strict_loads(open(report_path).read())
    assert report["failed"] is True
    assert "FileNotFoundError" in report["error"]
    assert report["graph"] is None and report["config"] is None
    for k in REPORT_KEYS:
        assert k in report
    assert _strict_loads(open(trace_path).read())["traceEvents"] is not None
    assert obs_trace.get_tracer() is obs_trace.NULL_TRACER


def test_seqfile_per_file_spans_stay_lazy(tmp_path):
    """Tracing records one span per segment file (with its record
    count) while the record stream stays a generator — lazily consumed
    records arrive BEFORE the file's span is recorded."""
    from pagerank_tpu.ingest.seqfile import (iter_segment_records,
                                             write_sequence_file)

    paths = []
    for i in range(2):
        p = str(tmp_path / f"metadata-0000{i}")
        write_sequence_file(p, [
            (f"http://site{i}.test/p{j}",
             json.dumps({"content": {"links": []}}))
            for j in range(3)
        ])
        paths.append(p)
    tr = obs.enable_tracing()
    it = iter_segment_records(paths, workers=1)
    first = next(it)  # streams: a record exists before any file span
    assert not [s for s in tr.spans() if s.name == "ingest/seqfile_file"]
    rest = list(it)
    assert 1 + len(rest) == 6 and first is not None
    spans = [s for s in tr.spans() if s.name == "ingest/seqfile_file"]
    assert [s.attrs["records"] for s in spans] == [3, 3]
    assert [s.attrs["path"] for s in spans] == paths


def test_environment_fingerprint_degrades_on_backend_failure(monkeypatch):
    """A broken backend must yield a report-able fingerprint (None
    fields + backend_error), never a raise — the failing run is the
    one most worth a report."""
    import jax

    def boom(*a, **k):
        raise RuntimeError("backend init failed")

    monkeypatch.setattr(jax, "default_backend", boom)
    monkeypatch.setattr(jax, "process_count", boom)
    env = obs.environment_fingerprint()
    assert env["backend"] is None and env["device_kind"] is None
    assert env["process_count"] is None
    assert "backend init failed" in env["backend_error"]
    assert env["jax_version"]  # the import half still fingerprints


def test_obs_report_cli(tmp_path, capsys):
    from pagerank_tpu.cli import main as cli_main
    from pagerank_tpu.obs.__main__ import main as obs_main

    a = str(tmp_path / "a.json")
    b = str(tmp_path / "b.json")
    for path, iters in ((a, 3), (b, 5)):
        assert cli_main([
            "--synthetic", "uniform:200:1000", "--engine", "cpu",
            "--iters", str(iters), "--log-every", "0",
            "--run-report", path,
        ]) == 0
    capsys.readouterr()
    assert obs_main(["report", a]) == 0
    out = capsys.readouterr().out
    assert "run report" in out and "solve/step" in out
    assert obs_main(["report", a, b]) == 0
    out = capsys.readouterr().out
    assert "phase wall deltas" in out
    assert obs_main(["report", str(tmp_path / "missing.json")]) == 2
