"""Serving-layer tests (ISSUE 18): admission, cache, daemon, chaos
determinism, contract form, CLI smoke."""

import numpy as np
import pytest

from pagerank_tpu import PageRankConfig, build_graph
from pagerank_tpu.serving import (AdmissionQueue, BatchWallModel, Draining,
                                  Overloaded, PendingQuery, PprServer,
                                  QueryDeadlineExceeded, ResultCache,
                                  ServeConfig)
from pagerank_tpu.testing.faults import DeviceFaultSchedule
from pagerank_tpu.testing.load import (QueryLoadGenerator,
                                       install_serve_faults,
                                       run_serve_load)
from pagerank_tpu.testing.schedules import VirtualClock
from pagerank_tpu.utils import synth


@pytest.fixture(scope="module")
def graph():
    src, dst = synth.rmat_edges(8, edge_factor=8, seed=3)
    return build_graph(src, dst, n=256)


def frozen_wall(wall_s):
    return BatchWallModel(initial_s=wall_s, alpha=0.0)


def pending(clock, qid=0, source=0, k=4, deadline_s=10.0):
    now = clock()
    return PendingQuery(qid=qid, source=source, k=k,
                        deadline=now + deadline_s, t_submit=now)


def serve_config(**kw):
    base = dict(max_batch=4, queue_depth=16, deadline_ms=400.0, topk=8,
                wall_alpha=0.0, wall_initial_s=0.05, cache_capacity=64,
                batch_margin_s=0.01)
    base.update(kw)
    return ServeConfig(**base)


def make_server(graph, clock, liveness_probe=None, **sc_kw):
    srv = PprServer(graph, config=PageRankConfig(num_iters=5),
                    serve_config=serve_config(**sc_kw),
                    liveness_probe=liveness_probe, clock=clock)
    srv.start(dispatcher=False)
    return srv


# -- admission / wall model -------------------------------------------------


def test_wall_model_alpha_zero_freezes():
    m = frozen_wall(0.1)
    m.observe(5.0)
    assert m.estimate() == 0.1
    m2 = BatchWallModel(initial_s=0.1, alpha=0.5)
    m2.observe(0.3)
    assert m2.estimate() == pytest.approx(0.2)


def test_admission_rejects_when_queue_full():
    clock = VirtualClock()
    q = AdmissionQueue(max_batch=2, queue_depth=2,
                       wall_model=frozen_wall(0.01), clock=clock)
    q.offer(pending(clock, qid=0))
    q.offer(pending(clock, qid=1))
    with pytest.raises(Overloaded) as e:
        q.offer(pending(clock, qid=2))
    assert "queue full" in str(e.value)
    assert e.value.retry_after_s > 0
    assert e.value.outcome == "shed_overload"


def test_admission_predictive_shed():
    # Frozen 0.2s batch wall, one-query batches: the second query has
    # two batches ahead of it (0.4s modeled) but only 0.3s of deadline
    # left -> shed AT ADMISSION, with a truthful retry-after.
    clock = VirtualClock()
    q = AdmissionQueue(max_batch=1, queue_depth=64,
                       wall_model=frozen_wall(0.2), clock=clock)
    q.offer(pending(clock, qid=0, deadline_s=10.0))
    with pytest.raises(Overloaded) as e:
        q.offer(pending(clock, qid=1, deadline_s=0.3))
    assert e.value.retry_after_s >= 0.1 - 1e-9
    # The same deadline with an empty queue admits fine.
    q2 = AdmissionQueue(max_batch=1, queue_depth=64,
                        wall_model=frozen_wall(0.2), clock=clock)
    q2.offer(pending(clock, qid=0, deadline_s=0.3))


def test_batch_closes_at_max_size_or_deadline_margin():
    clock = VirtualClock()
    q = AdmissionQueue(max_batch=2, queue_depth=16, batch_margin_s=0.01,
                       wall_model=frozen_wall(0.05), clock=clock)
    assert q.try_close_batch() is None  # empty
    q.offer(pending(clock, qid=0))
    q.offer(pending(clock, qid=1))
    batch = q.try_close_batch()  # full
    assert [p.qid for p in batch] == [0, 1]
    q.batch_done()
    # One query, far deadline: accumulates until the margin is reached.
    q.offer(pending(clock, qid=2, deadline_s=1.0))
    assert q.try_close_batch() is None
    clock.advance(0.95)  # remaining 0.05 <= wall 0.05 + margin 0.01
    batch = q.try_close_batch()
    assert [p.qid for p in batch] == [2]


def test_drain_closes_admission_and_flushes_typed():
    clock = VirtualClock()
    q = AdmissionQueue(max_batch=4, queue_depth=16,
                       wall_model=frozen_wall(0.05), clock=clock)
    p0, p1 = pending(clock, qid=0), pending(clock, qid=1)
    q.offer(p0)
    q.offer(p1)
    q.close()
    with pytest.raises(Draining):
        q.offer(pending(clock, qid=2))
    # Draining also closes a partial batch (no arrivals will top it up).
    batch = q.try_close_batch()
    assert [p.qid for p in batch] == [0, 1]
    q.batch_done()
    # Whatever the drain deadline strands gets typed-rejected, not dropped.
    p3 = pending(clock, qid=3)
    q._queue.append(p3)  # bypass closed admission to stage a stranded query
    assert q.flush_rejected(lambda _q: Draining("drain deadline")) == 1
    assert p3.outcome == "rejected_draining"


# -- result cache -----------------------------------------------------------


def test_result_cache_lru_eviction_and_disable():
    c = ResultCache(capacity=2)
    k1, k2, k3 = [ResultCache.key("fp", s, ("p",), 4) for s in (1, 2, 3)]
    c.put(k1, np.arange(4), np.ones(4))
    c.put(k2, np.arange(4), np.ones(4))
    assert c.get(k1) is not None  # touch: k1 becomes most-recent
    c.put(k3, np.arange(4), np.ones(4))
    assert c.get(k2) is None  # k2 was LRU -> evicted
    assert c.get(k1) is not None and c.get(k3) is not None
    off = ResultCache(capacity=0)
    off.put(k1, np.arange(4), np.ones(4))
    assert off.get(k1) is None and len(off) == 0


# -- daemon (pump mode, virtual clock) --------------------------------------


def test_server_answers_and_serves_repeat_from_cache(graph):
    clock = VirtualClock()
    srv = make_server(graph, clock)
    q1 = srv.submit(7, k=4)
    clock.advance(0.36)  # into the close margin, before expiry
    assert srv.pump() == 1
    assert q1.outcome == "answered"
    ids1, scores1 = q1.result(timeout=0)
    assert ids1.shape == (4,) and scores1.shape == (4,)
    # Same (source, k, params): LRU hit at admission, never queued.
    q2 = srv.submit(7, k=4)
    assert q2.outcome == "answered_cache"
    ids2, scores2 = q2.result(timeout=0)
    np.testing.assert_array_equal(ids1, ids2)
    np.testing.assert_array_equal(scores1, scores2)
    srv.drain()


def test_server_rejects_expired_in_queue_typed(graph):
    clock = VirtualClock()
    srv = make_server(graph, clock, cache_capacity=0)
    q = srv.submit(3, k=4, deadline_s=0.1)
    clock.advance(0.2)  # expires IN QUEUE
    srv.pump()
    assert q.outcome == "rejected_deadline"
    with pytest.raises(QueryDeadlineExceeded):
        q.result(timeout=0)
    srv.drain()


def test_server_submit_requires_start(graph):
    srv = PprServer(graph, config=PageRankConfig(num_iters=5),
                    serve_config=serve_config())
    with pytest.raises(RuntimeError):
        srv.submit(0)


def test_server_rescues_and_reruns_inflight_batch(graph):
    import jax

    ndev = len(jax.devices())
    clock = VirtualClock()
    sched = DeviceFaultSchedule(seed=11, kill={0: 1})
    srv = make_server(graph, clock, liveness_probe=sched.liveness_probe,
                      cache_capacity=0)
    install_serve_faults(srv, sched, clock=clock, service_s=0.05)
    q = srv.submit(9, k=4)
    clock.advance(0.36)
    srv.pump()  # batch 0: kill -> rescue -> RE-RUN -> answered
    assert q.outcome == "answered_degraded"
    assert srv.degraded and srv.device_count == ndev - 1
    assert srv.rescues_done == 1
    srv.drain()


def test_chaos_load_replays_bit_identical(graph):
    import jax

    ndev = len(jax.devices())

    def one_run():
        clock = VirtualClock()
        sched = DeviceFaultSchedule(seed=7, kill={2: 5})
        srv = PprServer(graph, config=PageRankConfig(num_iters=5),
                        serve_config=serve_config(),
                        liveness_probe=sched.liveness_probe, clock=clock)
        srv.start(dispatcher=False)
        install_serve_faults(srv, sched, clock=clock, service_s=0.05)
        plan = QueryLoadGenerator(seed=7, num_queries=24, n=256,
                                  mean_gap_s=0.02, k=8).plan()
        return run_serve_load(srv, clock, plan, drain_at=20,
                              drain_deadline_s=1.0)

    r1, r2 = one_run(), one_run()
    assert r1["unsettled"] == 0 and r2["unsettled"] == 0
    assert r1["results_digest"] == r2["results_digest"]
    assert r1["admission_log"] == r2["admission_log"]
    assert r1["degraded"] and r1["device_count"] == ndev - 1
    assert r1["outcomes"].get("rejected_draining", 0) >= 1
    answered = sum(v for k, v in r1["outcomes"].items()
                   if k.startswith("answered"))
    assert answered >= 1


# -- contract form + CLI ----------------------------------------------------


def test_ppr_batch_contract_form_clean():
    from pagerank_tpu.analysis.contracts import run_contracts

    assert run_contracts(["ppr_batch"]) == []


def test_serve_cli_smoke_in_process(capsys):
    from pagerank_tpu import serve

    rc = serve.main(["--serve-smoke", "6", "--scale", "6",
                     "--edge-factor", "4", "--iters", "3",
                     "--topk", "8", "--max-batch", "4"])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"unsettled": 0' in out
