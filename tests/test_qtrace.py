"""Query-plane tests (ISSUE 19): cross-thread per-query tracing,
exemplar-linked tail latency, the serving flight recorder, the
disarmed-path booby trap, W3C traceparent at the HTTP edge, and the
monotonic-clock pin on serving deadline math."""

import glob
import json
import os
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from pagerank_tpu import PageRankConfig, build_graph
from pagerank_tpu.obs import live as obs_live
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.obs import trace as obs_trace
from pagerank_tpu.serving import (PprServer, ServeConfig, qtrace)
from pagerank_tpu.serving.admission import AdmissionQueue, BatchWallModel
from pagerank_tpu.serving.http import (QueryIngress, format_traceparent,
                                       parse_traceparent)
from pagerank_tpu.serving.query import PendingQuery
from pagerank_tpu.testing.faults import DeviceFaultSchedule
from pagerank_tpu.testing.load import (QueryLoadGenerator,
                                       install_serve_faults,
                                       run_serve_load)
from pagerank_tpu.testing.schedules import VirtualClock
from pagerank_tpu.utils import synth

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def graph():
    src, dst = synth.rmat_edges(8, edge_factor=8, seed=3)
    return build_graph(src, dst, n=256)


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts and ends disarmed (the process-global default)."""
    qtrace.disarm_query_plane()
    yield
    qtrace.disarm_query_plane()
    obs_trace.disable_tracing()


def serve_config(**kw):
    base = dict(max_batch=4, queue_depth=16, deadline_ms=400.0, topk=8,
                wall_alpha=0.0, wall_initial_s=0.05, cache_capacity=64,
                batch_margin_s=0.01)
    base.update(kw)
    return ServeConfig(**base)


def make_server(graph, clock, **sc_kw):
    srv = PprServer(graph, config=PageRankConfig(num_iters=5),
                    serve_config=serve_config(**sc_kw), clock=clock)
    srv.start(dispatcher=False)
    return srv


# -- the zero-cost pin (the booby trap) -------------------------------------


class BombTracer:
    """Any tracer call on the disarmed hot path is a test failure."""

    enabled = False

    def _bomb(self, *a, **k):  # pragma: no cover - the trap
        raise AssertionError("tracer touched on the disarmed serve path")

    span = add_span = add_event = _bomb
    start_span = finish_span = set_thread_label = _bomb


def test_disarmed_booby_trap(graph, monkeypatch):
    """With the query plane DISARMED, an admitted query makes ZERO
    tracer calls and ZERO exemplar (trace-id-carrying) histogram
    records on the admission/dispatch hot path — the acceptance
    criterion pinning 'observability off' as byte-identical work."""
    assert qtrace.get_query_plane() is None
    orig_record = obs_metrics.Histogram.record

    def guarded_record(self, v, trace_id=None):
        assert trace_id is None, "exemplar recorded while disarmed"
        return orig_record(self, v)

    monkeypatch.setattr(obs_metrics.Histogram, "record", guarded_record)
    monkeypatch.setattr(obs_trace, "_TRACER", BombTracer())
    clock = VirtualClock()
    srv = make_server(graph, clock)
    # Miss -> admitted -> dispatched; then a cache hit; then a shed.
    q1 = srv.submit(7, k=4)
    clock.advance(0.36)
    srv.pump()
    q2 = srv.submit(7, k=4)                      # cache hit path
    assert q1.outcome == "answered"
    assert q2.outcome == "answered_cache"
    assert q1.trace is None and q2.trace is None
    srv.drain()


def test_trace_id_carried_even_disarmed(graph):
    """Every typed outcome carries a W3C-shaped trace id, armed or
    not: the deterministic qid+1 fallback, or the adopted upstream id."""
    clock = VirtualClock()
    srv = make_server(graph, clock)
    q = srv.submit(3, k=4)
    assert re.fullmatch(r"[0-9a-f]{32}", q.trace_id)
    assert q.trace_id == format(q.qid + 1, "032x")
    adopted = "ab" * 16
    q2 = srv.submit(4, k=4, trace_id=adopted)
    assert q2.trace_id == adopted
    srv.drain()


# -- armed trace assembly ----------------------------------------------------


def test_armed_phases_links_and_outcomes(graph):
    """Armed: every settle carries the full phase timeline (admission
    decision, batch close reason, dispatch, fetch), batch-mates are
    span-linked to each other, and the cache path records its hit."""
    qtrace.arm_query_plane()
    plane = qtrace.get_query_plane()
    clock = VirtualClock()
    srv = make_server(graph, clock, max_batch=2)
    qa = srv.submit(11, k=4)
    qb = srv.submit(12, k=4)
    srv.pump()          # closes full (max_batch=2)
    qc = srv.submit(11, k=4)      # cache hit
    assert qa.outcome == "answered" and qc.outcome == "answered_cache"
    assert plane.settled_count == 3

    ta, tb, tc = qa.trace, qb.trace, qc.trace
    names_a = [p["name"] for p in ta.phases]
    assert names_a == ["query/cache", "query/admission",
                       "query/batch_wait", "query/dispatch",
                       "query/fetch"]
    attrs = {p["name"]: p.get("attrs", {}) for p in ta.phases}
    assert attrs["query/cache"] == {"hit": False}
    assert attrs["query/admission"] == {"decision": "admitted"}
    assert attrs["query/batch_wait"]["close_reason"] == "full"
    assert attrs["query/batch_wait"]["batch_size"] == 2
    assert attrs["query/dispatch"]["rerun"] is False
    # Batch membership via links, both directions, never self.
    assert ta.links == [tb.trace_id]
    assert tb.links == [ta.trace_id]
    assert ta.outcome == "answered"
    # Cache path: one query/cache phase with hit=True, nothing else.
    assert [p["name"] for p in tc.phases] == ["query/cache"]
    assert tc.phases[0]["attrs"] == {"hit": True}
    assert tc.outcome == "answered_cache"
    srv.drain()


def test_armed_shed_and_draining_settle_typed(graph):
    """Sheds and drain rejections settle their traces with the typed
    outcome + admission decision attr (no silent trace drops)."""
    qtrace.arm_query_plane()
    plane = qtrace.get_query_plane()
    clock = VirtualClock()
    srv = make_server(graph, clock, queue_depth=1, max_batch=1,
                      cache_capacity=0)
    srv.submit(1, k=4)
    q_shed = srv.submit(2, k=4)     # queue full -> shed
    assert q_shed.outcome == "shed_overload"
    srv.drain()
    q_drain = srv.submit(3, k=4)
    assert q_drain.outcome == "rejected_draining"
    shapes = {t.outcome for t in plane._ring}
    assert {"shed_overload", "rejected_draining"} <= shapes
    tr = q_shed.trace
    assert tr.phases[-1]["attrs"]["decision"] == "shed_overload"


def test_tracer_mirror_cross_thread_tree(graph):
    """With the process tracer armed too, the query's phases land as
    handle-parented spans under one root per query — a single trace
    tree even when phases come from different threads — and the Chrome
    export carries thread_name metadata lanes."""
    tracer = obs_trace.enable_tracing()
    tracer.set_thread_label(threading.get_ident(), "test-main")
    qtrace.arm_query_plane()
    srv = PprServer(graph, config=PageRankConfig(num_iters=5),
                    serve_config=serve_config())
    srv.start()          # REAL dispatcher thread
    try:
        q = srv.submit(9, k=4, deadline_s=5.0)
        ids, scores = q.result(timeout=10.0)
        assert len(ids) == 4
    finally:
        srv.drain()
    obs_trace.disable_tracing()
    spans = tracer.spans()
    roots = [s for s in spans if s.name == "query"]
    assert len(roots) == 1
    root = roots[0]
    children = [s for s in spans if s.parent_id == root.span_id]
    child_names = {s.name for s in children}
    assert "query/batch_wait" in child_names
    assert "query/dispatch" in child_names
    # The dispatch-side phases ran on the dispatcher thread: the tree
    # crosses threads while staying parented to the one root.
    assert {s.tid for s in spans if s.name.startswith("query/")} >= \
        {root.tid} or len({s.tid for s in children}) >= 1
    ev = tracer.chrome_events()
    meta = [e for e in ev if e.get("ph") == "M"
            and e.get("name") == "thread_name"]
    labels = {e["args"]["name"] for e in meta}
    assert {"test-main", "serve-dispatch"} <= labels


def test_closed_batch_reasons():
    """AdmissionQueue batches carry WHY they closed: full at max size,
    deadline at the close margin, drain at shutdown."""
    clock = VirtualClock()

    def q_(qid, deadline_s=10.0):
        now = clock()
        return PendingQuery(qid=qid, source=qid, k=4,
                            deadline=now + deadline_s, t_submit=now)

    aq = AdmissionQueue(max_batch=2, queue_depth=16, batch_margin_s=0.01,
                        wall_model=BatchWallModel(initial_s=0.05, alpha=0.0),
                        clock=clock)
    aq.offer(q_(0))
    aq.offer(q_(1))
    b = aq.try_close_batch()
    assert list(b) == [b[0], b[1]] and b.close_reason == "full"
    aq.batch_done()
    aq.offer(q_(2, deadline_s=1.0))
    clock.advance(0.95)
    b2 = aq.try_close_batch()
    assert b2.close_reason == "deadline"
    aq.batch_done()
    aq.offer(q_(3))
    aq.close()
    b3 = aq.try_close_batch()
    assert b3.close_reason == "drain"


# -- determinism with tracing armed ------------------------------------------


def test_chaos_determinism_with_tracing_armed(graph):
    """Satellite (b): same seed => same span tree (structure digest)
    AND same settle outcomes, with the plane and tracer both armed —
    instrumentation must not perturb the chaos harness's replay."""
    def one(armed):
        if armed:
            obs_trace.enable_tracing()
            qtrace.arm_query_plane()
        try:
            clock = VirtualClock()
            sched = DeviceFaultSchedule(seed=7, kill={2: 5})
            srv = PprServer(graph, config=PageRankConfig(num_iters=5),
                            serve_config=serve_config(),
                            liveness_probe=sched.liveness_probe,
                            clock=clock)
            srv.start(dispatcher=False)
            install_serve_faults(srv, sched, clock=clock, service_s=0.05)
            plan = QueryLoadGenerator(seed=7, num_queries=16, n=256,
                                      mean_gap_s=0.02, k=8).plan()
            return run_serve_load(srv, clock, plan, drain_at=None)
        finally:
            if armed:
                qtrace.disarm_query_plane()
                obs_trace.disable_tracing()

    r1 = one(armed=True)
    r2 = one(armed=True)
    r0 = one(armed=False)
    assert r1["trace_digest"] == r2["trace_digest"]
    assert r1["admission_log"] == r2["admission_log"]
    assert r1["results_digest"] == r2["results_digest"]
    # Arming must not change WHAT happened, only record it.
    assert r0["admission_log"] == r1["admission_log"]
    assert r0["results_digest"] == r1["results_digest"]
    assert "trace_digest" not in r0


# -- W3C traceparent at the HTTP edge ----------------------------------------


def test_parse_traceparent_grammar():
    tid = "a" * 32
    assert parse_traceparent(f"00-{tid}-{'b' * 16}-01") == tid
    # Uppercase tolerated (lowercased), surrounding whitespace stripped.
    assert parse_traceparent(f" 00-{tid.upper()}-{'B' * 16}-01 ") == tid
    # Invalid: all-zero ids, wrong lengths, garbage, empty, None.
    assert parse_traceparent(f"00-{'0' * 32}-{'b' * 16}-01") is None
    assert parse_traceparent(f"00-{tid}-{'0' * 16}-01") is None
    assert parse_traceparent(f"00-{tid[:-1]}-{'b' * 16}-01") is None
    assert parse_traceparent("not-a-traceparent") is None
    assert parse_traceparent("") is None
    assert parse_traceparent(None) is None


def test_format_traceparent_roundtrips():
    clock = VirtualClock()
    q = PendingQuery(qid=41, source=0, k=4, deadline=10.0,
                     t_submit=clock())
    tp = format_traceparent(q.trace_id, q.qid)
    assert re.fullmatch(r"00-[0-9a-f]{32}-[0-9a-f]{16}-01", tp)
    assert parse_traceparent(tp) == q.trace_id


def test_http_traceparent_roundtrip(graph):
    """`/ppr` accepts an upstream traceparent (the query adopts its
    trace id), returns one on every response, and degrades malformed
    headers to a server-assigned id — never a 4xx."""
    srv = PprServer(graph, config=PageRankConfig(num_iters=5),
                    serve_config=serve_config())
    srv.start()
    try:
        with QueryIngress(srv, port=0) as ing:
            base = f"http://127.0.0.1:{ing.port}/ppr?source=5&k=4"
            sent = "c" * 32
            req = urllib.request.Request(
                base, headers={"traceparent": f"00-{sent}-{'d' * 16}-01"}
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                body = json.load(resp)
                assert resp.status == 200
                assert body["trace_id"] == sent
                hdr = resp.headers["traceparent"]
            assert parse_traceparent(hdr) == sent
            # Malformed header: served fine, server-assigned id.
            req2 = urllib.request.Request(
                base, headers={"traceparent": "garbage"}
            )
            with urllib.request.urlopen(req2, timeout=30) as resp2:
                body2 = json.load(resp2)
                assert resp2.status == 200
                assert re.fullmatch(r"[0-9a-f]{32}", body2["trace_id"])
                assert body2["trace_id"] != sent
                assert parse_traceparent(resp2.headers["traceparent"]) \
                    == body2["trace_id"]
    finally:
        srv.drain()


# -- monotonic-clock pin (satellite c) ---------------------------------------


def test_no_wall_clock_in_serving_deadline_math():
    """Static pin: no ``time.time(`` anywhere in serving/ — deadline
    arithmetic runs on the injected clock (default ``time.monotonic``),
    so an NTP step can never expire or extend a query."""
    for path in glob.glob(
        os.path.join(REPO, "pagerank_tpu", "serving", "*.py")
    ):
        with open(path, encoding="utf-8") as f:
            src = f.read()
        assert "time.time(" not in src, (
            f"{os.path.basename(path)} uses wall-clock time.time(); "
            "serving deadline math must stay monotonic"
        )


def test_ntp_step_does_not_move_deadlines(graph, monkeypatch):
    """Behavioral pin: a +/-1h wall-clock step mid-flight (time.time
    patched) changes NO admission or settle decision — the daemon
    never consults the wall clock."""
    clock = VirtualClock()
    srv = make_server(graph, clock)
    q1 = srv.submit(21, k=4)
    # The NTP step lands while q1 is queued.
    monkeypatch.setattr(time, "time", lambda: time.monotonic() + 3600.0)
    clock.advance(0.36)
    srv.pump()
    assert q1.outcome == "answered"
    monkeypatch.setattr(time, "time", lambda: time.monotonic() - 3600.0)
    q2 = srv.submit(22, k=4)
    clock.advance(0.36)
    srv.pump()
    assert q2.outcome == "answered"
    srv.drain()


# -- exemplars and the OpenMetrics exporter (satellite d) --------------------

_OM_VALUE = r"(?:[-+]?(?:\d+\.?\d*(?:[eE][-+]?\d+)?|Inf)|NaN)"
_OM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r" " + _OM_VALUE +
    r'( # \{trace_id="[^"]+"\} ' + _OM_VALUE + r")?$"
)


def _assert_openmetrics_strict(text):
    lines = text.splitlines()
    assert lines[-1] == "# EOF"
    samples = exemplars = 0
    for line in lines[:-1]:
        if not line or line.startswith("# HELP ") \
                or line.startswith("# TYPE "):
            continue
        assert _OM_SAMPLE.match(line), f"bad line: {line!r}"
        samples += 1
        exemplars += " # {" in line
    return samples, exemplars


def test_histogram_exemplars_only_with_trace_id():
    reg = obs_metrics.MetricsRegistry()
    h = reg.histogram("t.lat_ms", "test")
    h.record(3.0)
    assert h.exemplars_view() == {}      # plain records never allocate
    h.record(3.0, trace_id="e" * 32)
    h.record(700.0, trace_id="f" * 32)
    ex = h.exemplars_view()
    assert {e["trace_id"] for e in ex.values()} == {"e" * 32, "f" * 32}
    snap = h.snapshot()
    assert snap["exemplars"] == ex
    # Snapshot omits the key entirely when no exemplar was recorded.
    h2 = reg.histogram("t.plain_ms", "test")
    h2.record(1.0)
    assert "exemplars" not in h2.snapshot()


def test_render_openmetrics_exemplars_strict():
    """The OpenMetrics rendering: counters ``_total``-suffixed,
    exemplar clauses on the buckets that hold trace-id records
    (including +Inf), NaN/Inf gauge spellings co-existing with
    exemplars, and the ``# EOF`` terminator — all under the strict
    grammar. The Prometheus fallback stays exemplar-free."""
    reg = obs_metrics.MetricsRegistry()
    reg.counter("t.hits", "h").inc(3)
    reg.gauge("t.nan", "n").set(float("nan"))
    reg.gauge("t.inf", "i").set(float("inf"))
    reg.gauge("t.ninf", "i").set(float("-inf"))
    h = reg.histogram("t.lat_ms", "l")
    h.record(3.0, trace_id="a1" * 16)
    h.record(1e19, trace_id="b2" * 16)    # beyond 2^63: the +Inf bucket
    om = obs_live.render_openmetrics(reg)
    samples, exemplars = _assert_openmetrics_strict(om)
    assert samples > 0 and exemplars == 2
    assert "pagerank_t_hits_total 3" in om
    assert 'pagerank_t_lat_ms_bucket{le="+Inf"}' in om
    inf_line = [l for l in om.splitlines()
                if l.startswith('pagerank_t_lat_ms_bucket{le="+Inf"}')][0]
    assert 'trace_id="' + "b2" * 16 + '"' in inf_line
    assert "NaN" in om and "+Inf" in om and "-Inf" in om
    # Plain-Prometheus fallback: same data, no exemplars, no EOF.
    prom = obs_live.render_prometheus(reg)
    assert " # {" not in prom
    assert "# EOF" not in prom
    assert "pagerank_t_hits 3" in prom


def test_exporter_format_dispatch():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("t.c", "c").inc()
    exp = obs_live.MetricsExporter(port=0, registry=reg,
                                   format="openmetrics")
    try:
        assert exp._CONTENT_TYPES["openmetrics"].startswith(
            "application/openmetrics-text")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exp.port}/metrics", timeout=10
        ) as resp:
            ctype = resp.headers["Content-Type"]
            body = resp.read().decode()
        assert ctype.startswith("application/openmetrics-text")
        assert body.rstrip("\n").endswith("# EOF")
    finally:
        exp.close()
    with pytest.raises(ValueError):
        obs_live.MetricsExporter(port=0, registry=reg, format="nope")


def test_serve_latency_exemplars_from_armed_queries(graph):
    """End-to-end: armed queries stamp their trace ids onto the
    serve.latency_ms buckets, and the exporter renders them."""
    reg = obs_metrics.get_registry()
    reg.reset()
    qtrace.arm_query_plane()
    clock = VirtualClock()
    srv = make_server(graph, clock)
    q = srv.submit(13, k=4)
    clock.advance(0.36)
    srv.pump()
    assert q.outcome == "answered"
    h = reg.histogram("serve.latency_ms", "")
    ex = h.exemplars_view()
    assert any(e["trace_id"] == q.trace_id for e in ex.values())
    om = obs_live.render_openmetrics()
    assert f'trace_id="{q.trace_id}"' in om
    _assert_openmetrics_strict(om)
    srv.drain()
    reg.reset()


# -- slow-query log and flight recorder --------------------------------------


def test_slow_query_log_strict_jsonl(graph, tmp_path):
    """Settles >= --slow-query-ms write ONE strict-JSON line each with
    the pinned schema; faster settles write nothing."""
    log = str(tmp_path / "slow.jsonl")
    qtrace.arm_query_plane(slow_query_ms=60.0, slow_query_path=log)
    plane = qtrace.get_query_plane()
    clock = VirtualClock()
    srv = make_server(graph, clock)
    q_slow = srv.submit(31, k=4)
    clock.advance(0.36)          # waits ~360ms -> slow
    srv.pump()
    q_fast = srv.submit(31, k=4)  # cache hit, 0ms -> not slow
    srv.drain()
    assert q_slow.outcome == "answered"
    assert q_fast.outcome == "answered_cache"
    assert plane.slow_count == 1
    qtrace.disarm_query_plane()   # closes the file

    def reject(s):
        raise AssertionError(f"non-strict constant {s!r}")

    with open(log, encoding="utf-8") as f:
        recs = [json.loads(line, parse_constant=reject) for line in f]
    assert len(recs) == 1
    rec = recs[0]
    assert set(rec) == set(qtrace.SLOW_QUERY_KEYS)
    assert rec["type"] == "slow_query"
    assert rec["trace_id"] == q_slow.trace_id
    assert rec["latency_ms"] >= 60.0
    assert [p["name"] for p in rec["phases"]][-1] == "query/fetch"


def test_flight_recorder_ring_and_dump_reasons(graph):
    """The ring keeps the last N settled timelines; drain and rescue
    each snapshot it; the report section carries the dumps."""
    qtrace.arm_query_plane(ring_size=4)
    plane = qtrace.get_query_plane()
    clock = VirtualClock()
    srv = make_server(graph, clock, cache_capacity=0)
    for i in range(6):
        srv.submit(40 + i, k=4)
        clock.advance(0.36)
        srv.pump()
    srv.drain()
    assert plane.settled_count == 6
    dump = plane.flight_dump("fatal")
    assert dump["reason"] == "fatal"
    assert len(dump["traces"]) == 4          # ring_size bound
    # drain() already pushed its own dump before ours.
    sec = plane.report_section()
    assert sec["enabled"] is True
    reasons = [d["reason"] for d in sec["flight_dumps"]]
    assert reasons[-2:] == ["drain", "fatal"]
    assert all(
        re.fullmatch(r"[0-9a-f]{32}", t["trace_id"])
        for d in sec["flight_dumps"] for t in d["traces"]
    )


def test_rescue_triggers_flight_dump(graph):
    qtrace.arm_query_plane()
    plane = qtrace.get_query_plane()
    clock = VirtualClock()
    sched = DeviceFaultSchedule(seed=7, kill={0: 5})
    srv = PprServer(graph, config=PageRankConfig(num_iters=5),
                    serve_config=serve_config(),
                    liveness_probe=sched.liveness_probe, clock=clock)
    srv.start(dispatcher=False)
    install_serve_faults(srv, sched, clock=clock, service_s=0.05)
    q = srv.submit(8, k=4)
    clock.advance(0.36)
    srv.pump()
    assert q.outcome == "answered_degraded"
    reasons = [d["reason"] for d in plane._dumps]
    assert "rescue" in reasons
    tr = q.trace
    disp = [p for p in tr.phases if p["name"] == "query/dispatch"][0]
    assert disp["attrs"]["rerun"] is True
    assert disp["attrs"]["attempts"] == 2
    srv.drain()


def test_report_serving_section():
    """The run report always carries a ``serving`` section: disarmed
    -> {"enabled": False}; armed -> the plane's live section."""
    from pagerank_tpu.obs import report as obs_report

    assert "serving" in obs_report.REPORT_KEYS
    rep = obs_report.build_run_report()
    assert rep["serving"] == {"enabled": False}
    qtrace.arm_query_plane(slow_query_ms=5.0)
    rep2 = obs_report.build_run_report()
    assert rep2["serving"]["enabled"] is True
    assert rep2["serving"]["slow_query_ms"] == 5.0
    assert set(rep2["serving"]["phase_p99_ms"]) == \
        set(qtrace.DECOMPOSITION_LEGS)
    # The rendered report mentions the section without crashing.
    assert "serving" in obs_report.render_report(rep2).lower()


# -- plane internals ---------------------------------------------------------


def test_phase_p99_math_and_empty_legs():
    plane = qtrace.QueryPlane()
    tr = plane.new_trace(0, 5, qtrace.default_trace_id(0), start_s=0.0)
    for i in range(100):
        tr.phases.append({"name": "query/dispatch", "start_s": 0.0,
                          "duration_s": (i + 1) / 1000.0,
                          "tid": 0})
    plane.settle(tr, "answered", 1.0, 100.0)
    p99 = plane.phase_p99_ms()
    assert p99["dispatch"] == pytest.approx(99.0)
    assert p99["admission_wait"] == 0.0     # no samples -> 0.0
    assert p99["batch_wait"] == 0.0 and p99["fetch"] == 0.0


def test_structure_digest_ignores_timestamps_and_tids():
    def build(start, tid):
        plane = qtrace.QueryPlane()
        for qid in (0, 1):
            tr = plane.new_trace(qid, 5, qtrace.default_trace_id(qid),
                                 start_s=start)
            tr.phases.append({"name": "query/dispatch",
                              "start_s": start + qid,
                              "duration_s": 0.5 * (qid + 1), "tid": tid})
            tr.link(qtrace.default_trace_id(1 - qid))
            plane.settle(tr, "answered", start + 2, 100.0)
        return plane.structure_digest()

    assert build(0.0, 111) == build(99.0, 222)
    # ... but a structural change (outcome) moves it.
    plane = qtrace.QueryPlane()
    tr = plane.new_trace(0, 5, qtrace.default_trace_id(0), start_s=0.0)
    plane.settle(tr, "rejected_deadline", 1.0, None)
    tr2 = plane.new_trace(1, 5, qtrace.default_trace_id(1), start_s=0.0)
    plane.settle(tr2, "answered", 1.0, 1.0)
    assert plane.structure_digest() != build(0.0, 111)


def test_default_trace_id_never_all_zero():
    assert qtrace.default_trace_id(0) == "0" * 31 + "1"
    assert all(qtrace.default_trace_id(i) != "0" * 32 for i in range(64))


# -- bounded-memory + publish-last regression pins (review fixes) ------------


def test_structure_digest_order_independent_and_o1_memory():
    """The digest is a rolling per-trace-hash sum folded in at settle:
    settle order must not move it (threads interleave settles), and the
    plane must retain NO per-query list — a lifetime-armed daemon stays
    O(1) in query count."""
    def build(order):
        plane = qtrace.QueryPlane()
        trs = []
        for qid in (0, 1, 2):
            tr = plane.new_trace(qid, qid, qtrace.default_trace_id(qid),
                                 start_s=0.0)
            tr.phases.append({"name": "query/dispatch", "start_s": 0.0,
                              "duration_s": 0.1 * (qid + 1), "tid": 7})
            trs.append(tr)
        for i in order:
            plane.settle(trs[i], "answered", 1.0, 5.0)
        return plane, plane.structure_digest()

    p1, d1 = build([0, 1, 2])
    p2, d2 = build([2, 0, 1])
    assert d1 == d2
    assert not hasattr(p1, "_settled")   # the unbounded ledger is gone
    # Bounded state only: ring + samples are deques with maxlen.
    assert p1._ring.maxlen is not None
    assert all(dq.maxlen is not None for dq in p1._samples.values())


def test_sealed_trace_ignores_post_settle_phase_appends():
    """After settle seals a trace, a late phase (the ingress thread's
    query/serialize) must not mutate the settled record or move the
    digest — it mirrors only into the live tracer."""
    tracer = obs_trace.enable_tracing()
    qtrace.arm_query_plane()
    plane = qtrace.get_query_plane()
    tr = plane.new_trace(0, 1, qtrace.default_trace_id(0), start_s=0.0)
    tr.phase("query/fetch", 0.0, 0.1)
    plane.settle(tr, "answered", 1.0, 100.0)
    digest = plane.structure_digest()
    tr.phase("query/serialize", 1.0, 0.01)
    assert [p["name"] for p in tr.phases] == ["query/fetch"]
    assert plane.structure_digest() == digest
    # ... but the live span tree still shows the serialize lane.
    assert "query/serialize" in {s.name for s in tracer.spans()}


def test_settle_happens_before_resolve_publishes(graph):
    """resolve() is the LAST step of every settle path: when the
    waiting thread wakes, the trace is already sealed and counted, so
    post-wake work can never race the dispatcher on the timeline."""
    qtrace.arm_query_plane()
    plane = qtrace.get_query_plane()
    srv = PprServer(graph, config=PageRankConfig(num_iters=5),
                    serve_config=serve_config())
    srv.start()          # REAL dispatcher thread
    try:
        q = srv.submit(17, k=4, deadline_s=5.0)
        q.result(timeout=10.0)
        assert plane.settled_count == 1
        assert q.trace._sealed is True
    finally:
        srv.drain()


def test_serialize_phase_stays_out_of_settled_record(graph):
    """End-to-end over HTTP: query/serialize shows in the live Chrome
    lanes but never in the flight-recorder ring (the settled record)."""
    tracer = obs_trace.enable_tracing()
    qtrace.arm_query_plane()
    plane = qtrace.get_query_plane()
    srv = PprServer(graph, config=PageRankConfig(num_iters=5),
                    serve_config=serve_config())
    srv.start()
    try:
        with QueryIngress(srv, port=0) as ing:
            url = f"http://127.0.0.1:{ing.port}/ppr?source=6&k=4"
            with urllib.request.urlopen(url, timeout=30) as resp:
                assert resp.status == 200
    finally:
        srv.drain()
    obs_trace.disable_tracing()
    ring_names = {p["name"] for t in plane._ring for p in t.phases}
    assert "query/fetch" in ring_names
    assert "query/serialize" not in ring_names
    assert "query/serialize" in {s.name for s in tracer.spans()}


def test_tracer_max_spans_ring():
    """Tracer(max_spans=N) keeps the most recent N finished spans — the
    bounded mode the daemon's --query-trace capture runs in."""
    tr = obs_trace.Tracer(max_spans=10)
    for i in range(25):
        sp = tr.start_span(f"s{i}")
        tr.finish_span(sp)
    spans = tr.spans()
    assert len(spans) == 10
    assert spans[0].name == "s15" and spans[-1].name == "s24"
    # Default stays unbounded (finite solver captures export it all).
    tr2 = obs_trace.Tracer()
    for i in range(25):
        tr2.finish_span(tr2.start_span(f"t{i}"))
    assert len(tr2.spans()) == 25


def test_serve_cli_rejects_half_slow_query_pair(tmp_path, capsys):
    """--slow-query-ms and --slow-query-log are a pair: half of it is a
    silent no-op, so the CLI refuses it at parse time (exit 2)."""
    from pagerank_tpu.serve.__main__ import main

    with pytest.raises(SystemExit) as e1:
        main(["--slow-query-ms", "5"])
    assert e1.value.code == 2
    with pytest.raises(SystemExit) as e2:
        main(["--slow-query-log", str(tmp_path / "slow.jsonl")])
    assert e2.value.code == 2
    err = capsys.readouterr().err
    assert "must be given together" in err
