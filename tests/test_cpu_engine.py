"""Golden tests (SURVEY.md §4): the vectorized float64 CPU oracle must
reproduce the RDD transliteration of `Sparky.java` iterate-by-iterate —
per-iteration snapshots diffed, not just the final vector — and
hand-computed values on the 4-node/6-edge toy graph (BASELINE config 1).
"""

import numpy as np
import pytest

from pagerank_tpu import PageRankConfig, ReferenceCpuEngine
from pagerank_tpu.ingest import records_to_graph
from tests.oracle_rdd import sparky_pagerank

# BASELINE.json config 1: 4 nodes / 6 edges, damping 0.85, 10 iters.
TOY_RECORDS = [
    ("a", ["b", "c"]),
    ("b", ["c", "a"]),
    ("c", ["a", "d"]),
    ("d", []),  # crawled page with no anchor links -> dangling
]


def run_engine_history(records, num_iters=10, **cfg_kw):
    graph, ids = records_to_graph(records)
    cfg = PageRankConfig(num_iters=num_iters, **cfg_kw)
    eng = ReferenceCpuEngine(cfg).build(graph)
    history = []
    eng.run(on_iteration=lambda i, info: history.append(eng.ranks().copy()))
    return graph, ids, history


def assert_matches_transliteration(records, num_iters=10):
    _, sparky_hist, all_urls, _ = sparky_pagerank(records, num_iters)
    graph, ids, hist = run_engine_history(records, num_iters)
    assert graph.n == len(all_urls)
    assert len(hist) == len(sparky_hist) == num_iters
    for it, (mine, ref) in enumerate(zip(hist, sparky_hist)):
        for url, rank in ref.items():
            vid = ids.get(url)
            assert vid is not None, url
            assert mine[vid] == pytest.approx(rank, abs=1e-12), (it, url)


def test_toy_matches_transliteration_per_iteration():
    assert_matches_transliteration(TOY_RECORDS)


def test_toy_hand_computed_first_iteration():
    _, ids, hist = run_engine_history(TOY_RECORDS, num_iters=1)
    r1 = hist[0]
    # r0=1 each; N=4; no zero-in-degree vertices. "d" is CRAWLED (record
    # ("d", [])) so the repair pass removes it from dangUrls
    # (Sparky.java:172-184, lookup() returns a non-null Iterable([null]))
    # => dangling mass m = 0. d emits nothing (urlCount decremented to 0).
    # a: 0.15+0.85*(0.5+0.5); b: 0.15+0.85*0.5; c: same as a; d: same as b.
    assert r1[ids.get("a")] == pytest.approx(1.0)
    assert r1[ids.get("b")] == pytest.approx(0.575)
    assert r1[ids.get("c")] == pytest.approx(1.0)
    assert r1[ids.get("d")] == pytest.approx(0.575)


def test_uncrawled_target_carries_dangling_mass():
    # "x" is an uncrawled target: the only kind of vertex that survives
    # the repair pass in dangUrls. With records a->x, b->a, its mass must
    # show up in every vertex's update.
    records = [("a", ["x"]), ("b", ["a"])]
    _, ids, hist = run_engine_history(records, num_iters=1)
    r1 = hist[0]
    # r0=1 each, N=3, m = r0[x] = 1, m/N = 1/3. in: a<-b, x<-a; b none.
    # a: 0.15+0.85*(1 + 1/3); x: same; b (zero-in, keeps old rank):
    # 0.15+0.85*(1 + 1/3).
    expect = 0.15 + 0.85 * (1 + 1 / 3)
    for u in ("a", "x", "b"):
        assert r1[ids.get(u)] == pytest.approx(expect)
    assert_matches_transliteration(records, num_iters=10)


def test_uncrawled_target_and_zero_in_degree():
    # "x" is linked-to but never crawled (graph completion,
    # Sparky.java:137-161); "lonely" has no in-links, so the
    # subtractByKey retention quirk (§2a.1) applies to it every iter.
    records = [
        ("a", ["b", "x"]),
        ("b", ["a"]),
        ("lonely", ["a", "b"]),
    ]
    assert_matches_transliteration(records, num_iters=10)


def test_duplicate_records_and_repair_pass():
    # "a" is marked dangling by one record but has outlinks in another —
    # the reference's repair pass (Sparky.java:172-184) un-dangles it.
    records = [
        ("a", []),
        ("a", ["b"]),
        ("b", ["a", "a"]),  # duplicate edges collapse (§2a.5)
    ]
    assert_matches_transliteration(records, num_iters=10)


def test_self_loop():
    records = [("a", ["a", "b"]), ("b", [])]
    assert_matches_transliteration(records, num_iters=10)


def test_randomized_graphs_match_transliteration():
    rng = np.random.default_rng(42)
    urls = [f"u{i}" for i in range(25)]
    extra = [f"x{i}" for i in range(6)]  # sometimes-uncrawled targets
    for trial in range(8):
        records = []
        for u in urls:
            for _ in range(int(rng.integers(0, 3))):  # 0-2 records per url
                k = int(rng.integers(0, 5))
                pool = urls + extra
                targets = [pool[int(rng.integers(0, len(pool)))] for _ in range(k)]
                records.append((u, targets))
        if not records:
            records = [("u0", [])]
        assert_matches_transliteration(records, num_iters=6)


def test_textbook_mode_conserves_probability_mass():
    cfg = PageRankConfig(num_iters=25, semantics="textbook")
    eng = ReferenceCpuEngine(cfg).build(records_to_graph(TOY_RECORDS)[0])
    r = eng.run()
    assert r.sum() == pytest.approx(1.0, abs=1e-12)
    assert np.all(r > 0)


def test_tol_early_stop():
    cfg = PageRankConfig(num_iters=500, tol=1e-10)
    eng = ReferenceCpuEngine(cfg).build(records_to_graph(TOY_RECORDS)[0])
    eng.run()
    assert eng.iteration < 500  # converged and stopped early
