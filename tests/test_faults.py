"""The fault-tolerance layer (ISSUE 3 / docs/ROBUSTNESS.md): retry
policy in virtual time, deterministic seeded fault injection, snapshot
checksums + corrupt-skip fallback, S3 wire-level retries against the
stub (503-then-success, connection reset mid-body, non-blind multipart
complete recovery), the async writer's retry / warn-and-drop / close
semantics, and the end-to-end seeded chaos runs: same seed -> same
fault schedule bit-for-bit, and a faulted run either matches the CPU
oracle or fails loudly — never a silent drop or corruption.
"""

import json
import random
import threading
import warnings

import numpy as np
import pytest

from pagerank_tpu import PageRankConfig, ReferenceCpuEngine, build_graph
from pagerank_tpu.engine import SolverHealthError
from pagerank_tpu.testing.faults import (
    FaultInjectedError,
    FaultInjectingFileSystem,
    FaultSchedule,
    HttpFaultInjector,
)
from pagerank_tpu.utils import fsio
from pagerank_tpu.utils.config import RobustnessConfig
from pagerank_tpu.utils.retry import RetryPolicy, RetryStats
from pagerank_tpu.utils.s3 import S3FileSystem, _s3_retryable
from pagerank_tpu.utils.snapshot import (
    AsyncRankWriter,
    SinkGuard,
    SnapshotCorruptError,
    Snapshotter,
    TextDumper,
    resume_engine,
)

from tests.s3stub import S3Stub


class VirtualTime:
    """Injectable clock/sleep: the whole backoff schedule runs in zero
    wall-clock and every requested delay is recorded."""

    def __init__(self):
        self.now = 0.0
        self.sleeps = []

    def clock(self):
        return self.now

    def sleep(self, d):
        self.sleeps.append(d)
        self.now += d


# -- RetryPolicy in virtual time -------------------------------------------


def test_retry_succeeds_after_transient_failures():
    vt = VirtualTime()
    pol = RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=1.0,
                      seed=7, sleep=vt.sleep, clock=vt.clock)
    stats = RetryStats()
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 4:
            raise ConnectionResetError("transient")
        return "ok"

    assert pol.call(flaky, stats=stats) == "ok"
    assert state["n"] == 4
    assert stats.attempts == 4 and stats.retries == 3
    assert len(vt.sleeps) == 3 and vt.now == pytest.approx(stats.slept)


def test_retry_backoff_is_seeded_full_jitter():
    """The jitter stream is a pure function of the seed: delays are
    uniform(0, min(max_delay, base * 2**k)) drawn from random.Random —
    reproduced here draw for draw (virtual-time backoff assertion)."""
    vt = VirtualTime()
    pol = RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=1.0,
                      seed=42, sleep=vt.sleep, clock=vt.clock)

    def always_fail():
        raise TimeoutError("nope")

    with pytest.raises(TimeoutError):
        pol.call(always_fail)
    ref = random.Random(42)
    expected = [ref.uniform(0.0, min(1.0, 0.1 * 2 ** k)) for k in range(4)]
    assert vt.sleeps == expected
    # same seed, fresh policy -> the identical schedule, bit for bit
    vt2 = VirtualTime()
    pol2 = RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=1.0,
                       seed=42, sleep=vt2.sleep, clock=vt2.clock)
    with pytest.raises(TimeoutError):
        pol2.call(always_fail)
    assert vt2.sleeps == vt.sleeps


def test_retry_non_retryable_raises_immediately():
    vt = VirtualTime()
    pol = RetryPolicy(max_attempts=5, sleep=vt.sleep, clock=vt.clock)
    calls = {"n": 0}

    def semantic():
        calls["n"] += 1
        raise FileNotFoundError("missing key")

    with pytest.raises(FileNotFoundError):
        pol.call(semantic)
    assert calls["n"] == 1 and vt.sleeps == []


def test_retry_deadline_bounds_the_schedule():
    vt = VirtualTime()
    pol = RetryPolicy(max_attempts=50, base_delay=1.0, max_delay=1.0,
                      deadline=2.5, seed=0, sleep=vt.sleep, clock=vt.clock)
    calls = {"n": 0}

    def fail():
        calls["n"] += 1
        raise ConnectionError("x")

    with pytest.raises(ConnectionError):
        pol.call(fail)
    assert vt.now <= 2.5
    assert calls["n"] < 50  # the deadline, not the attempt cap, stopped it


# -- FaultSchedule determinism ---------------------------------------------


def test_fault_schedule_same_seed_same_decisions():
    def drive(seed):
        s = FaultSchedule(seed=seed, fail_rate=0.2, truncate_rate=0.1,
                          max_faults=10)
        for i in range(50):
            s.decide("open_r" if i % 2 else "commit", f"p{i}")
        return s.log

    assert drive(11) == drive(11)
    assert drive(11) != drive(12)


def test_fault_injecting_fs_fail_nth_is_transient_and_logged():
    inner = fsio.MemoryFileSystem()
    sched = FaultSchedule(seed=0, fail_nth=(2,))
    fs = FaultInjectingFileSystem(inner, sched)
    with fs.open("mock://d/a", "wb") as f:  # commit = call 1
        f.write(b"x")
    with pytest.raises(FaultInjectedError):
        fs.open("mock://d/a", "rb")  # open_r = call 2 -> injected
    with fs.open("mock://d/a", "rb") as f:  # call 3: clean again
        assert f.read() == b"x"
    assert [a for _, _, _, a in sched.log] == ["-", "fail", "-"]
    # an injected fault is retryable by the default policy
    assert RetryPolicy().retryable(FaultInjectedError("x"))


def test_fault_fs_truncate_on_write_publishes_detectable_corruption():
    """A truncated snapshot write is PUBLISHED (the store can't know)
    but the checksum catches it at load — the never-silently-corrupt
    contract."""
    inner = fsio.MemoryFileSystem()
    # call 1 = makedirs (Snapshotter init), call 2 = the save's commit
    sched = FaultSchedule(seed=3, truncate_nth=(2,), ops=("commit",))
    fsio.register("chaos", FaultInjectingFileSystem(inner, sched))
    try:
        s = Snapshotter("chaos://ck", "fp", "reference")
        s.save(1, np.arange(64, dtype=np.float64))
        assert s.iterations() == [1]
        with pytest.raises(SnapshotCorruptError):
            s.load(1)
        assert s.load_latest_valid() is None
    finally:
        fsio.unregister("chaos")


# -- snapshot checksums + corrupt-skip fallback ----------------------------


def toy_graph(seed=0, n=60, e=400):
    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


CFG = PageRankConfig(num_iters=10, dtype="float64", accum_dtype="float64")


def test_snapshot_checksum_detects_content_tamper(tmp_path):
    """A VALID npz whose ranks were swapped after checksumming (valid
    zip, wrong bytes) must fail the checksum — not just zip CRC."""
    s = Snapshotter(str(tmp_path), "fp", "reference")
    s.save(3, np.arange(8, dtype=np.float64))
    with fsio.fopen(s.path(3), "rb") as f, np.load(f) as z:
        stored = bytes(z["checksum"])
    with fsio.fopen(s.path(3), "wb") as f:
        np.savez(f, ranks=np.zeros(8), iteration=np.int64(3),
                 fingerprint=np.bytes_(b"fp"),
                 semantics=np.bytes_(b"reference"),
                 checksum=np.bytes_(stored))
    with pytest.raises(SnapshotCorruptError, match="checksum"):
        s.load(3)


def test_snapshot_garbage_and_truncation_detected(tmp_path):
    s = Snapshotter(str(tmp_path), "fp", "reference")
    s.save(2, np.ones(16))
    raw = (tmp_path / "ranks_iter2.npz").read_bytes()
    (tmp_path / "ranks_iter2.npz").write_bytes(raw[: len(raw) // 2])
    with pytest.raises(SnapshotCorruptError):
        s.load(2)
    (tmp_path / "ranks_iter2.npz").write_bytes(b"not a zip at all")
    with pytest.raises(SnapshotCorruptError):
        s.load(2)


def test_resume_falls_back_to_newest_valid_snapshot(tmp_path):
    g = toy_graph()
    s = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    vecs = {i: np.full(g.n, float(i)) for i in (1, 2, 3, 4)}
    for i, v in vecs.items():
        s.save(i, v)
    # newest corrupt (garbage), next truncated -> fall back to 2
    (tmp_path / "ranks_iter4.npz").write_bytes(b"garbage")
    raw = (tmp_path / "ranks_iter3.npz").read_bytes()
    (tmp_path / "ranks_iter3.npz").write_bytes(raw[:40])
    eng = ReferenceCpuEngine(CFG).build(g)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        assert resume_engine(eng, s) == 2
    np.testing.assert_array_equal(eng.ranks(), vecs[2])
    # all corrupt -> clean no-resume, never a crash
    for i in (1, 2):
        (tmp_path / f"ranks_iter{i}.npz").write_bytes(b"junk")
    eng2 = ReferenceCpuEngine(CFG).build(g)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        assert resume_engine(eng2, s) == 0


# -- self-healing solve loop -----------------------------------------------


def _nan_bomb(eng, at_iteration, repeat=False):
    """Wrap eng.step: poison the solver state (and the step info) the
    first time iteration ``at_iteration`` executes — a transient
    soft-error model. ``repeat`` poisons EVERY attempt (persistent)."""
    orig = eng.step
    state = {"fired": 0}

    def step():
        info = orig()
        if eng.iteration == at_iteration and (repeat or not state["fired"]):
            state["fired"] += 1
            eng._r = eng._r * np.nan
            return {k: float("nan") for k in info}
        return info

    eng.step = step
    return state


def test_self_healing_run_recovers_from_transient_nan(tmp_path):
    g = toy_graph()
    full = ReferenceCpuEngine(CFG).build(g).run()
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    eng = ReferenceCpuEngine(CFG).build(g)
    _nan_bomb(eng, at_iteration=5)
    r = eng.run(
        on_iteration=lambda i, info: snap.save(i + 1, eng.ranks()),
        snapshotter=snap,
    )
    assert eng.health["rollbacks"] == 1
    assert eng.health["first_bad_iteration"] == 5
    np.testing.assert_allclose(r, full, rtol=0, atol=1e-12)


def test_unhealthy_step_without_snapshotter_raises(tmp_path):
    g = toy_graph()
    eng = ReferenceCpuEngine(CFG).build(g)
    _nan_bomb(eng, at_iteration=2)
    with pytest.raises(SolverHealthError, match="iteration 2") as ei:
        eng.run()
    assert ei.value.first_bad_iteration == 2 and ei.value.rollbacks == 0


def test_persistent_fault_exhausts_budget_names_first_bad_iteration(tmp_path):
    g = toy_graph()
    cfg = CFG.replace(robustness=RobustnessConfig(max_rollbacks=2))
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    eng = ReferenceCpuEngine(cfg).build(g)
    _nan_bomb(eng, at_iteration=3, repeat=True)
    with pytest.raises(SolverHealthError, match="first bad iteration 3") as ei:
        eng.run(
            on_iteration=lambda i, info: snap.save(i + 1, eng.ranks()),
            snapshotter=snap,
        )
    assert ei.value.rollbacks == 2
    assert "budget (2) exhausted" in str(ei.value)


def test_mass_drift_check_triggers_rollback(tmp_path):
    g = toy_graph()
    full = ReferenceCpuEngine(CFG).build(g).run()
    cfg = CFG.replace(robustness=RobustnessConfig(mass_tol=0.5))
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    eng = ReferenceCpuEngine(cfg).build(g)
    orig = eng.step
    state = {"fired": False}

    def step():
        info = orig()
        if eng.iteration == 4 and not state["fired"]:
            state["fired"] = True
            eng._r = eng._r * 3.0  # finite info, silently inflated mass
        return info

    eng.step = step
    r = eng.run(
        on_iteration=lambda i, info: snap.save(i + 1, eng.ranks()),
        snapshotter=snap,
    )
    assert eng.health["rollbacks"] == 1
    np.testing.assert_allclose(r, full, rtol=0, atol=1e-12)


# -- S3 retries against the stub -------------------------------------------


@pytest.fixture
def s3rt():
    """Stub + filesystem whose retry policy runs on a virtual sleep (no
    real backoff wall-clock) with a pinned jitter seed."""
    with S3Stub() as stub:
        vt = VirtualTime()
        fs = S3FileSystem(
            stub.endpoint,
            retry_policy=RetryPolicy(
                max_attempts=4, base_delay=0.05, max_delay=0.5, seed=5,
                retryable=_s3_retryable, sleep=vt.sleep,
            ),
        )
        yield stub, fs, vt


def test_s3_503_slowdown_then_success(s3rt):
    stub, fs, vt = s3rt
    hits = {"PUT": 0, "GET": 0}

    def hook(method, path):
        if path == "/b/k" and method in hits:
            hits[method] += 1
            if hits[method] == 1:
                return ("status", 503, "SlowDown")
        return None

    stub.fault_hook = hook
    with fs.open("s3://b/k", "wb") as f:
        f.write(b"payload")
    assert stub.objects["/b/k"] == b"payload"
    with fs.open("s3://b/k", "rb") as f:
        assert f.read() == b"payload"
    assert hits == {"PUT": 2, "GET": 2}
    assert fs.retry_stats.retries == 2
    assert vt.sleeps and len(vt.sleeps) == 2  # backoff was virtual


def test_s3_connection_reset_mid_body_retries(s3rt):
    stub, fs, vt = s3rt
    with fs.open("s3://b/big", "wb") as f:
        f.write(bytes(range(256)) * 8)
    state = {"n": 0}

    def hook(method, path):
        if method == "GET" and path == "/b/big":
            state["n"] += 1
            if state["n"] == 1:
                return ("truncate", 100)  # full length, short body
        return None

    stub.fault_hook = hook
    with fs.open("s3://b/big", "rb") as f:
        assert f.read() == bytes(range(256)) * 8
    assert state["n"] >= 2 and fs.retry_stats.retries >= 1


def test_s3_dropped_connection_retries(s3rt):
    stub, fs, vt = s3rt
    stub.objects["/b/x"] = b"here"
    state = {"n": 0}

    def hook(method, path):
        if method == "HEAD":
            state["n"] += 1
            if state["n"] == 1:
                return ("reset",)  # no response at all
        return None

    stub.fault_hook = hook
    assert fs.isfile("s3://b/x")
    assert state["n"] == 2


def test_s3_multipart_complete_transient_then_relist_and_recomplete(s3rt):
    stub, fs, vt = s3rt
    fs.MULTIPART_PART_SIZE = 1024
    state = {"n": 0}

    def hook(method, path):
        if method == "POST" and "uploadId=" in path:
            state["n"] += 1
            if state["n"] == 1:
                return ("status", 500)
        return None

    stub.fault_hook = hook
    data = bytes(range(256)) * 17  # 5 parts
    with fs.open("s3://b/big.bin", "wb") as f:
        f.write(data)
    assert stub.objects["/b/big.bin"] == data
    assert state["n"] == 2  # re-completed only after a parts re-list
    assert not stub.uploads


def test_s3_multipart_complete_committed_but_response_lost(s3rt):
    """The non-idempotent case: the first complete COMMITS server-side
    but its response is lost. The client must NOT blindly re-POST (the
    upload is gone); it re-lists parts, sees NoSuchUpload, verifies the
    object exists, and treats the upload as done."""
    stub, fs, vt = s3rt
    fs.MULTIPART_PART_SIZE = 1024
    state = {"n": 0}

    def hook(method, path):
        if method == "POST" and "uploadId=" in path:
            state["n"] += 1
            if state["n"] == 1:
                return ("commit_then_status", 500)
        return None

    stub.fault_hook = hook
    data = b"q" * 5000
    with fs.open("s3://b/once.bin", "wb") as f:
        f.write(data)
    assert stub.objects["/b/once.bin"] == data
    assert state["n"] == 1  # never re-POSTed the complete
    assert stub.completed_multiparts == ["/b/once.bin"]


# -- AsyncRankWriter: retries, drop policy, close path ---------------------


def test_async_writer_retries_transient_sink_failures():
    seen = []
    state = {"n": 0}

    def flaky_sink(i, r):
        state["n"] += 1
        if state["n"] < 3:
            raise ConnectionResetError("blip")
        seen.append((i, float(r[0])))

    guard = SinkGuard(retry_policy=RetryPolicy(max_attempts=5, base_delay=0.0))
    with AsyncRankWriter(lambda p: p, [flaky_sink], guard=guard) as w:
        w.submit(0, np.ones(2))
    assert seen == [(0, 1.0)]
    assert guard.retries == 2 and guard.dropped == []


def test_async_writer_warn_and_drop_writes_dead_letter(tmp_path):
    dead = str(tmp_path / "dead_letter.json")

    def doomed_sink(i, r):
        raise IOError(f"disk full at {i}")

    guard = SinkGuard(
        retry_policy=RetryPolicy(max_attempts=2, base_delay=0.0),
        on_failure="warn_and_drop", dead_letter_path=dead,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with AsyncRankWriter(lambda p: p, [doomed_sink], guard=guard) as w:
            for i in range(3):
                w.submit(i, np.ones(2))
        # close() did NOT raise: the run survives, the drops are recorded
    assert [d["iteration"] for d in guard.dropped] == [0, 1, 2]
    manifest = json.loads((tmp_path / "dead_letter.json").read_text())
    assert [d["iteration"] for d in manifest["dropped"]] == [0, 1, 2]
    assert all("disk full" in d["error"] for d in manifest["dropped"])


def test_async_writer_error_after_final_submit_surfaces_at_exit():
    """Regression (ISSUE 3 satellite): a worker failure that lands
    AFTER the last submit must surface from close()/__exit__ — there is
    no later submit to observe it."""
    gate = threading.Event()

    def late_sink(i, r):
        gate.wait(timeout=10)
        raise IOError("late boom")

    with pytest.raises(RuntimeError, match="late boom"):
        with AsyncRankWriter(lambda p: p, [late_sink]) as w:
            w.submit(0, np.ones(2))
            gate.set()  # the failure happens strictly after this submit
    # close is idempotent AND keeps re-raising: no later caller path
    # (e.g. an outer finally) can exit cleanly over the lost write
    with pytest.raises(RuntimeError, match="late boom"):
        w.close()
    with pytest.raises(RuntimeError, match="submit\\(\\) after close"):
        w.submit(1, np.ones(2))


def test_cli_warn_and_drop_keeps_run_alive(tmp_path, monkeypatch):
    """CLI integration: a persistently failing snapshot write under
    --on-write-failure warn_and_drop completes the run, records the
    dropped iterations in dead_letter.json, and still writes the
    healthy snapshots."""
    from pagerank_tpu import cli as cli_mod
    from pagerank_tpu.utils import snapshot as snap_mod

    edges = tmp_path / "e.txt"
    edges.write_text("0 1\n1 2\n2 0\n")
    real_save = snap_mod.Snapshotter.save

    def failing_save(self, iteration, ranks):
        if iteration >= 4:
            raise IOError("disk full")
        return real_save(self, iteration, ranks)

    monkeypatch.setattr(snap_mod.Snapshotter, "save", failing_save)
    sd = tmp_path / "s"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rc = cli_mod.main([
            "--input", str(edges), "--iters", "5",
            "--snapshot-dir", str(sd), "--log-every", "0",
            "--on-write-failure", "warn_and_drop", "--write-retries", "1",
        ])
    assert rc == 0
    manifest = json.loads((sd / "dead_letter.json").read_text())
    assert [d["iteration"] for d in manifest["dropped"]] == [3, 4]
    assert sorted(p.name for p in sd.iterdir()) == [
        "dead_letter.json", "ranks_iter1.npz", "ranks_iter2.npz",
        "ranks_iter3.npz",
    ]


def test_text_dump_failure_leaves_no_parseable_part(tmp_path, monkeypatch):
    """A dump killed mid-write must never leave a parseable-looking
    part-00000 (satellite: TextDumper rides the same atomic
    tmp+rename path as Snapshotter.save)."""
    import pagerank_tpu.ingest.native as native_mod

    d = TextDumper(str(tmp_path / "dumps"))
    calls = {"n": 0}

    def dying_formatter(*a, **k):
        calls["n"] += 1
        if calls["n"] >= 2:
            raise OSError("killed mid-dump")
        return b"(0,1.0)\n" * 2

    monkeypatch.setattr(native_mod, "format_rank_lines_native",
                        dying_formatter)
    monkeypatch.setattr(TextDumper, "CHUNK_ROWS", 2)
    with pytest.raises(OSError, match="killed mid-dump"):
        d.dump(0, np.ones(6))
    out = tmp_path / "dumps" / "PageRank0"
    assert not (out / "part-00000").exists()
    assert not (out / "_SUCCESS").exists()


def test_sink_guard_never_swallows_interrupts():
    """warn_and_drop applies to write FAILURES only: a
    KeyboardInterrupt/SystemExit raised during a sink write must
    propagate, never be dead-lettered."""
    guard = SinkGuard(on_failure="warn_and_drop")

    def interrupted():
        raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        guard(0, interrupted)
    assert guard.dropped == []


def test_rollback_scan_skips_foreign_graph_snapshots(tmp_path):
    """match=True (the rollback contract): a snapshot from a different
    graph or semantics in a reused directory is skipped like
    corruption — never restored into the solver."""
    s_old = Snapshotter(str(tmp_path), "other-graph", "reference")
    s_old.save(5, np.ones(8))
    s = Snapshotter(str(tmp_path), "this-graph", "reference")
    s.save(2, np.full(8, 2.0))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        found = s.load_latest_valid(match=True)
    assert found is not None and found[0] == 2  # skipped the foreign 5
    # without match (the resume path) the newest still surfaces, so
    # resume_engine can RAISE on the mismatch instead of starting over
    assert s.load_latest_valid()[0] == 5


def test_writer_synced_snapshotter_drains_queue_before_scan(tmp_path):
    """Rollback must not race snapshots still in the async writer's
    queue: the WriterSyncedSnapshotter flushes first, so the scan sees
    every already-submitted iteration."""
    import time

    from pagerank_tpu.utils.snapshot import WriterSyncedSnapshotter

    snap = Snapshotter(str(tmp_path), "fp", "reference")

    def slow_save(i, ranks):
        time.sleep(0.05)
        snap.save(i + 1, ranks)

    with AsyncRankWriter(lambda p: p, [slow_save]) as w:
        for i in range(3):
            w.submit(i, np.full(4, float(i)))
        synced = WriterSyncedSnapshotter(snap, w)
        found = synced.load_latest_valid(max_iteration=3)
        assert found is not None and found[0] == 3
        assert synced.fingerprint == "fp" and synced.semantics == "reference"


def test_s3_retry_policy_none_disables_retries():
    with S3Stub() as stub:
        fs = S3FileSystem(stub.endpoint, retry_policy=None)
        calls = {"n": 0}

        def hook(method, path):
            calls["n"] += 1
            return ("status", 503, "SlowDown")

        stub.fault_hook = hook
        with pytest.raises(OSError, match="503"):
            with fs.open("s3://b/k", "wb") as f:
                f.write(b"x")
        assert calls["n"] == 1  # one attempt, no retry


def test_s3_multipart_lost_complete_with_stale_object_raises(s3rt):
    """Upload vanishes without committing (e.g. a lifecycle abort)
    while a PREVIOUS version of the key exists: mere key existence must
    not pass for success — the ETag check refuses, the caller sees the
    failure instead of trusting stale bytes."""
    stub, fs, vt = s3rt
    fs.MULTIPART_PART_SIZE = 1024
    stale = b"old snapshot content"
    with fs.open("s3://b/snap.bin", "wb") as f:
        f.write(stale)

    def hook(method, path):
        if method == "POST" and "uploadId=" in path:
            with stub.lock:  # server-side abort + transient answer
                stub.uploads.clear()
            return ("status", 500)
        return None

    stub.fault_hook = hook
    with pytest.raises(OSError, match="verifiable commit"):
        with fs.open("s3://b/snap.bin", "wb") as f:
            f.write(b"n" * 5000)
    assert stub.objects["/b/snap.bin"] == stale  # untouched


# -- seeded chaos runs (the acceptance criterion) --------------------------


def _fs_chaos_run(seed):
    """Full run() with per-iteration snapshots through a seeded
    FaultInjectingFileSystem: finite fault budget below the retry
    budget, so the run must complete. Returns (ranks, schedule log,
    snapshot validity map)."""
    inner = fsio.MemoryFileSystem()
    sched = FaultSchedule(seed=seed, fail_rate=0.08, truncate_rate=0.04,
                          max_faults=8)
    fs = FaultInjectingFileSystem(inner, sched, sleep=lambda s: None)
    fsio.register("chaos", fs)
    try:
        g = toy_graph(seed=1)
        snap = Snapshotter("chaos://run/ck", g.fingerprint(), "reference")
        guard = SinkGuard(
            retry_policy=RetryPolicy(max_attempts=6, base_delay=0.0, seed=seed)
        )
        eng = ReferenceCpuEngine(CFG).build(g)
        ranks = eng.run(
            on_iteration=lambda i, info: guard(
                i, lambda: snap.save(i + 1, eng.ranks())
            ),
            snapshotter=snap,
        )
        validity = {}
        for it in snap.iterations():
            try:
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore", RuntimeWarning)
                    r, _ = snap.load(it)
                validity[it] = ("valid", r)
            except SnapshotCorruptError:
                validity[it] = ("corrupt", None)
        return ranks, list(sched.log), validity, guard
    finally:
        fsio.unregister("chaos")


def test_fs_chaos_run_completes_correct_and_reproducible():
    r1, log1, validity1, guard1 = _fs_chaos_run(seed=23)
    r2, log2, validity2, _ = _fs_chaos_run(seed=23)
    # same seed -> the same fault schedule, bit for bit
    assert log1 == log2
    assert any(a != "-" for _, _, _, a in log1), "chaos run injected nothing"
    # faulted runs still produce ORACLE ranks
    oracle = ReferenceCpuEngine(CFG).build(toy_graph(seed=1)).run()
    np.testing.assert_allclose(r1, oracle, rtol=0, atol=1e-6)
    np.testing.assert_array_equal(r1, r2)
    # never a silent drop or corruption: every iteration's snapshot is
    # present (retries beat the fault budget) and every one that loads
    # matches the true trajectory; truncated ones are DETECTED
    assert sorted(validity1) == list(range(1, CFG.num_iters + 1))
    eng = ReferenceCpuEngine(CFG).build(toy_graph(seed=1))
    for it in range(1, CFG.num_iters + 1):
        eng.step()
        state, r = validity1[it]
        if state == "valid":
            np.testing.assert_array_equal(r, eng.ranks())


def _s3_chaos_run(seed):
    """The acceptance-criteria chaos run: snapshots live in an
    S3-protocol store whose wire randomly answers 5xx/SlowDown
    (seeded), the snapshot directory is CORRUPTED mid-run (garbage +
    truncation), and the solver state is poisoned with NaN — the run
    must roll back past the corrupt snapshots, retry the faulted
    requests, and land on oracle ranks."""
    with S3Stub() as stub:
        inj = HttpFaultInjector(seed=seed, fail_rate=0.04, max_faults=10)
        stub.fault_hook = inj
        fs = S3FileSystem(
            stub.endpoint,
            retry_policy=RetryPolicy(
                max_attempts=6, base_delay=0.0, max_delay=0.0, seed=seed,
                retryable=_s3_retryable, sleep=lambda s: None,
            ),
        )
        fsio.register("s3", fs)
        try:
            g = toy_graph(seed=2)
            snap = Snapshotter("s3://ck/run", g.fingerprint(), "reference")
            eng = ReferenceCpuEngine(CFG).build(g)
            orig = eng.step
            state = {"fired": False}

            def step():
                info = orig()
                if eng.iteration == 7 and not state["fired"]:
                    state["fired"] = True
                    # corrupt the snapshot directory mid-run: newest
                    # garbage, next truncated...
                    with fsio.fopen(snap.path(7), "wb") as f:
                        f.write(b"garbage, not a zip")
                    with fsio.fopen(snap.path(6), "rb") as f:
                        raw = f.read()
                    with fsio.fopen(snap.path(6), "wb") as f:
                        f.write(raw[: len(raw) // 3])
                    # ...and poison the solver state
                    eng._r = eng._r * np.nan
                    return {k: float("nan") for k in info}
                return info

            eng.step = step
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                ranks = eng.run(
                    on_iteration=lambda i, info: snap.save(
                        i + 1, eng.ranks()
                    ),
                    snapshotter=snap,
                )
            return ranks, list(inj.log), dict(eng.health), fs.retry_stats
        finally:
            fsio.unregister("s3")


def test_s3_chaos_run_matches_oracle_and_reproduces_bit_for_bit():
    r1, log1, health1, stats1 = _s3_chaos_run(seed=37)
    r2, log2, health2, _ = _s3_chaos_run(seed=37)
    # bit-for-bit reproducible wire-fault schedule across two runs
    assert log1 == log2
    assert any(a != "-" for _, _, _, a in log1), "no S3 faults injected"
    assert stats1.retries > 0, "no request was actually retried"
    # rollback skipped the corrupted 7/6 snapshots (fell back to 5)
    assert health1["rollbacks"] == 1
    assert health1["first_bad_iteration"] == 7
    # faulted, corrupted, rolled-back run still lands on oracle ranks
    oracle = ReferenceCpuEngine(CFG).build(toy_graph(seed=2)).run()
    np.testing.assert_allclose(r1, oracle, rtol=0, atol=1e-6)
    np.testing.assert_array_equal(r1, r2)
