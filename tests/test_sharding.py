"""Distributed-without-a-cluster tests (SURVEY.md §4): an 8-device fake
CPU mesh (conftest sets --xla_force_host_platform_device_count=8) must
agree with the single-device result, and the partitioner must preserve
the contribution sum under padding/chunking."""

import jax
import numpy as np
import pytest

from pagerank_tpu import JaxTpuEngine, PageRankConfig, build_graph
from pagerank_tpu.parallel import partition
from pagerank_tpu.parallel.mesh import make_mesh


def test_fake_mesh_has_8_devices():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("ndev", [2, 4, 8])
def test_sharded_matches_single_device(ndev):
    rng = np.random.default_rng(11)
    n, e = 300, 2500
    graph = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    cfg = PageRankConfig(num_iters=12, dtype="float64", accum_dtype="float64")
    r1 = JaxTpuEngine(cfg.replace(num_devices=1)).build(graph).run()
    rn = JaxTpuEngine(cfg.replace(num_devices=ndev)).build(graph).run()
    np.testing.assert_allclose(rn, r1, rtol=0, atol=1e-12)


def test_partition_shapes_and_padding():
    rng = np.random.default_rng(0)
    n, e = 50, 103  # deliberately not divisible by 8
    graph = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    shards = partition.partition_edges(graph, 8)
    assert shards.src.shape[0] % 8 == 0
    assert shards.num_real_edges == graph.num_edges
    pad = shards.src.shape[0] - graph.num_edges
    # padding is inert: weight 0, valid dst
    assert np.all(shards.weight[graph.num_edges :] == 0)
    assert np.all(shards.dst[graph.num_edges :] == n - 1)
    # per-chunk dst-sortedness (the sorted-segment-sum contract)
    per = shards.edges_per_shard
    for i in range(8):
        chunk = shards.dst[i * per : (i + 1) * per]
        assert np.all(np.diff(chunk.astype(np.int64)) >= 0)


def test_partition_preserves_contribution_sum():
    rng = np.random.default_rng(5)
    n, e = 64, 777
    graph = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    r = rng.random(n)
    dense = np.zeros(n)
    np.add.at(dense, graph.dst, graph.edge_weight * r[graph.src])
    shards = partition.partition_edges(graph, 8, weight_dtype=np.float64)
    acc = np.zeros(n)
    per = shards.edges_per_shard
    for i in range(8):
        sl = slice(i * per, (i + 1) * per)
        np.add.at(acc, shards.dst[sl], shards.weight[sl] * r[shards.src[sl]])
    np.testing.assert_allclose(acc, dense, rtol=1e-12)


def test_mesh_construction():
    m = make_mesh(4, "data")
    assert m.devices.size == 4
    assert m.axis_names == ("data",)
    with pytest.raises(ValueError):
        make_mesh(1000)


def test_empty_edge_graph_runs_sharded():
    # All vertices dangling (every page linkless): contribution sum is 0,
    # mass spreads uniformly.
    graph = build_graph(np.array([], dtype=np.int64), np.array([], dtype=np.int64), n=16)
    cfg = PageRankConfig(num_iters=5, dtype="float64", accum_dtype="float64")
    r = JaxTpuEngine(cfg).build(graph).run()
    # every vertex identical by symmetry
    assert np.allclose(r, r[0])
