"""The pair-packed wide-accumulation gather (ops/spmv.py:ell_contrib_pair)
— f64-grade accuracy from f32 gathers (the BASELINE.md 1e-6 L1 gate)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pagerank_tpu import JaxTpuEngine, PageRankConfig, ReferenceCpuEngine, build_graph
from pagerank_tpu.ops import ell as ell_lib, spmv


def _pack(rng, n=1024, e=8000):
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    return g, ell_lib.ell_pack(g)


@pytest.mark.parametrize("chunk", [None, 64])
def test_ell_contrib_pair_matches_f64_reference(chunk):
    rng = np.random.default_rng(0)
    g, pack = _pack(rng)
    n_state = pack.n_padded
    gw = 8
    srcs = np.where(pack.weight != 0, pack.src, np.int32(n_state))
    if chunk:
        rows = srcs.shape[0]
        padr = -(-rows // chunk) * chunk
        srcs = np.concatenate(
            [srcs, np.full((padr - rows, 128), n_state, np.int32)]
        )
        rb = np.concatenate(
            [pack.row_block, np.full(padr - rows, pack.num_blocks - 1, np.int32)]
        )
    else:
        rb = pack.row_block

    z64 = rng.random(n_state)  # f64
    hi = z64.astype(np.float32)
    lo = (z64 - hi.astype(np.float64)).astype(np.float32)
    pad = np.zeros(gw, np.float32)
    out = spmv.ell_contrib_pair(
        jnp.asarray(np.concatenate([hi, pad])),
        jnp.asarray(np.concatenate([lo, pad])),
        jnp.asarray(srcs), jnp.asarray(rb), pack.num_blocks,
        gather_width=gw, chunk_rows=chunk,
    )
    assert out.dtype == jnp.float64

    # numpy f64 oracle on the exact split values (weight-free slot form:
    # real slots select zs[src], inert slots contribute 0)
    zs = hi.astype(np.float64) + lo.astype(np.float64)
    v = np.where(pack.weight != 0, zs[np.minimum(pack.src, pack.n - 1)], 0.0)
    y2 = np.zeros((pack.num_blocks, 128))
    np.add.at(y2, pack.row_block, v)
    np.testing.assert_allclose(
        np.asarray(out)[: pack.n], y2.reshape(-1)[: pack.n], rtol=1e-13, atol=1e-13
    )


@pytest.mark.parametrize("dtype", ["float32", "float64"])
def test_pair_engine_close_to_oracle(dtype):
    rng = np.random.default_rng(5)
    g = build_graph(rng.integers(0, 3000, 40000), rng.integers(0, 3000, 40000), n=3000)
    cfg = PageRankConfig(
        num_iters=20, dtype=dtype, accum_dtype="float64", wide_accum="pair"
    )
    r_t = JaxTpuEngine(cfg).build(g).run_fast()
    r_c = ReferenceCpuEngine(cfg.replace(dtype="float64")).build(g).run()
    norm_l1 = np.abs(r_t - r_c).sum() / np.abs(r_c).sum()
    gate = 1e-7 if dtype == "float32" else 1e-12
    assert norm_l1 < gate, norm_l1


def test_wide_accum_requires_not_narrower_than_dtype():
    with pytest.raises(ValueError):
        PageRankConfig(dtype="float64", accum_dtype="float32").validate()


def test_pair_engine_wide_gather_width_matches_oracle(monkeypatch):
    """The occupancy-widened pair layouts run the gather at width 64
    (span 8.4M / 2^17 rows — engines/jax_engine.occupancy_span); force
    that width at toy scale so the wide-row pair gather semantics are
    pinned against the oracle without a 67M-vertex graph."""
    monkeypatch.setattr(JaxTpuEngine, "GATHER_WIDTH", 64)
    rng = np.random.default_rng(6)
    g = build_graph(rng.integers(0, 3000, 40000),
                    rng.integers(0, 3000, 40000), n=3000)
    cfg = PageRankConfig(
        num_iters=20, dtype="float64", accum_dtype="float64",
        wide_accum="pair",
    )
    eng = JaxTpuEngine(cfg).build(g)
    r_t = eng.run_fast()
    r_c = ReferenceCpuEngine(cfg).build(g).run()
    assert np.abs(r_t - r_c).sum() / np.abs(r_c).sum() < 1e-12
