"""Asynchronous stale-boundary halo exchange tests (ISSUE 17;
engines/jax_engine._setup_vs_halo_async; docs/PERF_NOTES.md "Hiding
the exchange"): the lag-0 exactness demand (bit-identical to the
synchronous vs_halo form, ZERO extra buffers — booby-trapped), the
priming invariant (first step after build/set_ranks/restore is lag-0
exact), lag-1 oracle parity at the f32 gate under textbook semantics,
the auto-gate's downgrade paths, retain/restore rebuilding the double
buffer (the elastic-rescue state path), SDC compatibility through the
staleness slack, same-seed bit-for-bit chaos reproducibility, and the
seed-deterministic rotation-protocol interleaving replay
(testing/schedules.rotation_actors)."""

import numpy as np
import pytest

import jax

from pagerank_tpu import JaxTpuEngine, PageRankConfig, build_graph
from pagerank_tpu import sdc as sdc_mod
from pagerank_tpu.engines.cpu import ReferenceCpuEngine
from pagerank_tpu.testing import schedules
from pagerank_tpu.testing.faults import (
    DeviceFaultSchedule,
    install_device_faults,
)
from pagerank_tpu.utils.metrics import oracle_l1
from pagerank_tpu.utils.synth import rmat_edges

NDEV = len(jax.devices())

needs_mesh = pytest.mark.skipif(NDEV < 8, reason="needs 8 fake devices")

F32_GATE = 1e-4


def _rmat_graph(scale=10, ef=8, seed=1):
    src, dst = rmat_edges(scale, edge_factor=ef, seed=seed)
    return build_graph(src, dst, n=1 << scale)


def _cfg(**kw):
    base = dict(num_iters=8, dtype="float32", accum_dtype="float32",
                num_devices=min(8, NDEV), vertex_sharded=True,
                halo_exchange=True)
    base.update(kw)
    return PageRankConfig(**base)


def _async_cfg(**kw):
    """Async halo config with the auto-gate pinned open (the tests
    measure the form itself; the gate's refusal paths get their own
    tests below)."""
    base = dict(halo_async=True, halo_async_min_gain=0.0)
    base.update(kw)
    return _cfg(**base)


def _engine(graph, cfg):
    return JaxTpuEngine(cfg).build(graph)


# -- lag-0 exactness (booby-trapped) ----------------------------------------


@needs_mesh
def test_lag0_is_bit_identical_to_sync_with_zero_buffers():
    """``--halo-async --stale-max-lag 0`` demands exactness: the engine
    must run the SYNCHRONOUS sparse exchange — bit-identical ranks, the
    vs_halo form, and (the booby trap) ZERO extra carry buffers. A
    lag-0 "async" path that kept a buffer would be paying the memory
    without the overlap AND hiding a second code path from the
    contract sweep."""
    g = _rmat_graph()
    sync = _engine(g, _cfg())
    lag0 = _engine(g, _async_cfg(stale_max_lag=0))
    assert lag0.layout_info()["form"] == "vs_halo"
    assert lag0.layout_info()["halo_async"] == "off:stale_max_lag=0"
    assert tuple(lag0._carry_args) == ()  # the booby trap
    np.testing.assert_array_equal(sync.run(), lag0.run())


@needs_mesh
def test_first_step_after_build_is_lag0_exact():
    """Priming from the freshly-built rank plane makes the FIRST async
    step consume a fresh boundary — bit-identical to the synchronous
    step. Staleness begins at step two, by construction, never by
    accident of initialization."""
    g = _rmat_graph()
    sync = _engine(g, _cfg())
    a = _engine(g, _async_cfg())
    assert a.layout_info()["form"] == "vs_halo_async"
    assert a.layout_info()["halo_async"] == "on:lag1"
    assert len(a._carry_args) > 0
    sync.step()
    a.step()
    np.testing.assert_array_equal(np.asarray(sync.ranks()),
                                  np.asarray(a.ranks()))


# -- lag-1 convergence: oracle parity ---------------------------------------


@needs_mesh
def test_lag1_converges_to_oracle_fixed_point_textbook():
    """The lag-1 schedule converges to the SAME fixed point as the f64
    CPU oracle under textbook semantics (the contraction guarantees
    one; reference semantics legitimately diverges on graphs with
    zero-in-degree vertices, so there is no fixed point to compare
    at). 120 iterations sits well past the tol-1e-6 convergence of
    both schedules at this scale; the gate is the repo-wide f32
    oracle-parity bound."""
    g = _rmat_graph()
    iters = 120
    a = _engine(g, _async_cfg(num_iters=iters, semantics="textbook"))
    r_async = a.run_fast()
    cfg_o = PageRankConfig(num_iters=iters, dtype="float64",
                           accum_dtype="float64", semantics="textbook")
    r_oracle = ReferenceCpuEngine(cfg_o).build(g).run()
    _l1, norm, _mass = oracle_l1(r_async, r_oracle)
    assert norm <= F32_GATE


# -- auto-gate downgrades ---------------------------------------------------


@needs_mesh
def test_gate_downgrades_on_low_predicted_gain():
    """A predicted overlap gain below config.halo_async_min_gain
    downgrades (logged, recorded) to the synchronous exchange: hiding
    an exchange that is already cheap buys staleness for nothing."""
    g = _rmat_graph()
    eng = _engine(g, _cfg(halo_async=True, halo_async_min_gain=1.0))
    li = eng.layout_info()
    assert li["form"] == "vs_halo"
    assert str(li["halo_async"]).startswith("off:gain ")
    # The downgraded engine is the synchronous form, bit for bit.
    np.testing.assert_array_equal(_engine(g, _cfg()).run(), eng.run())


def test_gate_refuses_single_device_mesh():
    """One device has no boundary to exchange, hence nothing to
    overlap — the gate refuses rather than building dead buffers."""
    g = _rmat_graph(scale=9)
    eng = _engine(g, _cfg(num_devices=1, halo_async=True,
                          halo_async_min_gain=0.0))
    li = eng.layout_info()
    assert str(li.get("halo_async", "")).startswith("off:")
    assert not str(li.get("halo_async", "")).startswith("on:")


# -- elastic/state path: the double buffer across state replacement ---------


@needs_mesh
def test_retain_restore_roundtrip_is_bitwise():
    """retain_state/restore_state must carry the boundary double
    buffer (and the staleness-slack scalar) so a restored solve
    continues bit-identically — the state path every redo and rescue
    rides."""
    g = _rmat_graph()
    eng = _engine(g, _async_cfg(num_iters=20))
    eng.run_fast(num_iters=5)
    token = eng.retain_state()
    eng.run_fast(num_iters=10)
    eng.restore_state(token)
    assert eng.iteration == 5
    r_resumed = eng.run_fast(num_iters=20)
    r_fresh = _engine(g, _async_cfg(num_iters=20)).run_fast()
    np.testing.assert_array_equal(np.asarray(r_resumed),
                                  np.asarray(r_fresh))


@needs_mesh
def test_rescue_rebuild_restores_double_buffer():
    """A rescue builds a FRESH engine and restores the retained token
    into it: the rebuilt engine must adopt the double buffer from the
    token and continue bit-identically with the uninterrupted solve."""
    g = _rmat_graph()
    eng = _engine(g, _async_cfg(num_iters=12))
    eng.run_fast(num_iters=4)
    token = eng.retain_state()
    rebuilt = _engine(g, _async_cfg(num_iters=12))
    rebuilt.restore_state(token)
    assert len(rebuilt._carry_args) > 0
    r_a = eng.run_fast()
    r_b = rebuilt.run_fast()
    np.testing.assert_array_equal(np.asarray(r_a), np.asarray(r_b))


@needs_mesh
def test_set_ranks_reprimes_to_lag0():
    """set_ranks replaces the rank plane, so the engine must re-prime
    the boundary buffer from the NEW ranks: the first step after is
    lag-0 exact — bit-identical to the SYNCHRONOUS engine stepping
    from the same ranks."""
    g = _rmat_graph()
    warm = _engine(g, _async_cfg())
    warm.run_fast(num_iters=3)
    v = np.asarray(warm.ranks())
    a = _engine(g, _async_cfg())
    s = _engine(g, _cfg())
    a.set_ranks(v)
    s.set_ranks(v)
    a.step()
    s.step()
    np.testing.assert_array_equal(np.asarray(a.ranks()),
                                  np.asarray(s.ranks()))


# -- SDC compatibility: the staleness slack ---------------------------------


@needs_mesh
def test_sdc_checked_async_solve_matches_unchecked():
    """The checked async solve produces the SAME ranks as the
    unchecked one on a clean run: the flow-conservation invariants
    absorb the bounded staleness through the slack term (the previous
    step's L1 delta — sdc.evaluate_check), so a legitimate lag-1 step
    is never misread as corruption."""
    g = _rmat_graph()
    plain = _engine(g, _async_cfg(num_iters=12)).run()
    sdc_mod.reset()
    checked = _engine(g, _async_cfg(num_iters=12,
                                    sdc_check_every=3)).run()
    np.testing.assert_array_equal(np.asarray(plain),
                                  np.asarray(checked))
    s = sdc_mod.report_section()
    assert s["checks"] == 4 and s["flips_detected"] == 0


@needs_mesh
def test_same_seed_chaos_is_bit_for_bit_on_async():
    """Two same-seed chaos runs over the async form must produce
    identical fault logs AND identical final ranks — detection, the
    slack-tolerant evaluation, redo, and healing included (the
    testing/faults.py reproducibility convention)."""
    g = _rmat_graph()

    def once():
        sdc_mod.reset()
        eng = _engine(g, _async_cfg(num_iters=10, sdc_check_every=1))
        sched = DeviceFaultSchedule(seed=23, flip={4: (2, "sign")})
        install_device_faults(eng, sched)
        ranks = eng.run()
        return list(sched.log), np.asarray(ranks)

    log_a, ranks_a = once()
    log_b, ranks_b = once()
    assert log_a == log_b
    assert any(entry[1] == "flip" for entry in log_a)
    np.testing.assert_array_equal(ranks_a, ranks_b)


# -- rotation protocol: seed-deterministic interleaving replay --------------


def test_rotation_protocol_clean_under_sampled_schedules():
    """The honest rotation protocol (rank plane adopted first, buffer
    second; prime on state replacement) holds its invariants —
    consumed lag <= stale_max_lag, reader never observes a buffer
    newer than the ranks — under every sampled schedule."""
    for seed in range(25):
        holder = {}
        schedules.replay(
            seed,
            lambda s: holder.update(st=schedules.rotation_actors(
                s, steps=6, rescue_after=3)),
        )
        st = holder["st"]
        assert st["violations"] == [], (seed, st["violations"])
        assert st["restores"] == 1


def test_rotation_protocol_booby_trap_skipping_prime():
    """The booby-trapped protocol (state replacement WITHOUT
    re-priming the buffer) must record a consumed-lag violation under
    the very same seeds the honest protocol survives — proving the
    replay can actually see the bug class it certifies against."""
    for seed in range(25):
        holder = {}
        schedules.replay(
            seed,
            lambda s: holder.update(st=schedules.rotation_actors(
                s, steps=6, rescue_after=3, prime_on_restore=False)),
        )
        assert any(v[1] == "consumed-lag"
                   for v in holder["st"]["violations"]), seed


def test_rotation_protocol_replay_is_seed_deterministic():
    """Same seed, same spawn sequence => identical schedule log,
    bit for bit (the testing/faults.py convention)."""
    runs = [
        schedules.replay(
            7, lambda s: schedules.rotation_actors(s, rescue_after=2)
        ).log
        for _ in range(2)
    ]
    assert runs[0] == runs[1]
