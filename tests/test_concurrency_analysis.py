"""The concurrency plane (ISSUE 14): the PTR static pass
(analysis/concurrency.py) — seeded-defect fixtures per rule, context
inference, the shared handler-root source of truth, the clean-tree
gate — and the deterministic interleaving replays
(testing/schedules.py) that reproduce the fixed GracefulDrain handler
race and demonstrate a waived watchdog race benign under every sampled
schedule."""

import ast
import json
import os
import random
import signal
import textwrap
import threading
import time

import pytest

from pagerank_tpu import jobs
from pagerank_tpu.analysis import concurrency as conc_mod
from pagerank_tpu.analysis import lint as lint_mod
from pagerank_tpu.analysis import load_allowlist, split_allowlisted
from pagerank_tpu.analysis import roots as roots_mod
from pagerank_tpu.analysis.__main__ import main as analysis_main
from pagerank_tpu.obs import live as obs_live
from pagerank_tpu.obs import metrics as obs_metrics
from pagerank_tpu.obs import trace as obs_trace
from pagerank_tpu.testing import schedules


def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def _rules_of(findings):
    return {f.rule for f in findings}


# -- seeded-defect fixtures: each rule fires on its synthetic defect --------

PTR_FIXTURES = {
    "PTR001": """
        import threading

        class Worker:
            def __init__(self):
                self.total = 0
                self._thread = threading.Thread(
                    target=self._run, name="acc", daemon=True)
                self._thread.start()

            def _run(self):
                self.total += 1   # written on the 'acc' thread

            def read(self):
                return self.total  # read on the main thread, no lock

            def stop(self):
                self._thread.join()
    """,
    "PTR002": """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        return 1

            def ba(self):
                with self._b:
                    with self._a:
                        return 2
    """,
    "PTR003": """
        import signal
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()
                self.flag = False
                signal.signal(signal.SIGTERM, self._on_term)

            def _on_term(self, signum, frame):
                print("terminating")   # I/O in handler context
                with self._lock:       # lock acquire in handler context
                    self.flag = True
    """,
    "PTR004": """
        import threading
        import time

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self.data = {}

            def refresh(self):
                with self._lock:
                    time.sleep(0.1)    # blocking while holding the lock
                    self.data["k"] = 1
    """,
    "PTR005": """
        import json
        import threading

        def _work():
            with open("/tmp/x.json", "w") as f:
                json.dump({}, f)       # durable write on a daemon thread

        def spawn():
            t = threading.Thread(target=_work, name="bg", daemon=True)
            t.start()                  # never joined anywhere

        def spawn_forever(handler):
            u = threading.Thread(target=handler, name="fg")
            u.start()                  # non-daemon, never joined
    """,
    "PTR006": """
        import threading
        import time

        class Poller:
            def __init__(self):
                self._last = 0.0
                self._thread = threading.Thread(
                    target=self._run, name="poller", daemon=True)
                self._thread.start()

            def _run(self):
                self._last = time.monotonic()  # raw clock in thread code

            def stop(self):
                self._thread.join()
    """,
}


@pytest.mark.parametrize("rule", sorted(PTR_FIXTURES))
def test_seeded_defect_fires_expected_rule(tmp_path, rule):
    path = _write(tmp_path, f"bad_{rule.lower()}.py", PTR_FIXTURES[rule])
    findings = conc_mod.analyze_file(path)
    assert rule in _rules_of(findings), [f.render() for f in findings]


@pytest.mark.parametrize("rule", sorted(PTR_FIXTURES))
def test_cli_exits_nonzero_per_rule(tmp_path, capsys, rule):
    path = _write(tmp_path, f"bad_{rule.lower()}.py", PTR_FIXTURES[rule])
    rc = analysis_main([path, "--lint-only", "--allowlist", "none",
                        "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert rule in {f["rule"] for f in out["findings"]}


def test_json_schema_is_stable_for_ptr_findings(tmp_path, capsys):
    """PTR findings ride the existing --json schema (version 1) —
    pinned alongside the PTL/PTC checks in tests/test_analysis.py."""
    path = _write(tmp_path, "bad.py", PTR_FIXTURES["PTR001"])
    rc = analysis_main([path, "--lint-only", "--allowlist", "none",
                        "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["version"] == 1
    assert set(out) == {"version", "ok", "counts", "findings", "waived"}
    f = next(f for f in out["findings"] if f["rule"].startswith("PTR"))
    assert set(f) == {"rule", "path", "line", "col", "message", "snippet"}


def test_fixed_variants_stay_quiet(tmp_path):
    """The discriminating half of each fixture: the same structure with
    the discipline applied (a common lock; the injectable clock) must
    produce ZERO PTR findings."""
    locked = _write(tmp_path, "locked.py", """
        import threading

        class Worker:
            def __init__(self):
                self.total = 0
                self._lock = threading.Lock()
                self._thread = threading.Thread(
                    target=self._run, name="acc", daemon=True)
                self._thread.start()

            def _run(self):
                with self._lock:
                    self.total += 1

            def read(self):
                with self._lock:
                    return self.total

            def stop(self):
                self._thread.join()
    """)
    assert conc_mod.analyze_file(locked) == []

    injectable = _write(tmp_path, "injectable.py", """
        import threading
        import time

        class Poller:
            def __init__(self, clock=time.monotonic):
                self._clock = clock
                self._last = 0.0
                self._thread = threading.Thread(
                    target=self._run, name="poller", daemon=True)
                self._thread.start()

            def _run(self):
                self._last = self._clock()

            def stop(self):
                self._thread.join()
    """)
    assert [f.rule for f in conc_mod.analyze_file(injectable)
            if f.rule == "PTR006"] == []


def test_module_level_thread_fixture_fires(tmp_path):
    """Thread creation at module TOP LEVEL (the natural standalone-
    fixture and script shape) must be discovered: the module body is
    scanned as a synthetic function, so its Thread sites root contexts
    and its joins count — while import-time writes stay construction-
    exempt (module constants never read as cross-context writes)."""
    p = _write(tmp_path, "toplevel.py", """
        import threading

        COUNTS = {}

        def _work():
            COUNTS["n"] = COUNTS.get("n", 0) + 1

        def read():
            return COUNTS.get("n")

        t = threading.Thread(target=_work, name="top-worker")
        t.start()
    """)
    rules = _rules_of(conc_mod.analyze_file(p))
    assert "PTR001" in rules  # cross-context COUNTS, no lock
    assert "PTR005" in rules  # non-daemon thread, never joined


def test_module_level_signal_install_discovered(tmp_path):
    p = _write(tmp_path, "toplevel_sig.py", """
        import signal

        def _handler(signum, frame):
            print("bye")

        signal.signal(signal.SIGTERM, _handler)
    """)
    findings = conc_mod.analyze_file(p)
    assert "PTR003" in _rules_of(findings), [f.render() for f in findings]


def test_in_package_directory_keeps_whole_program_view(capsys):
    """An in-package DIRECTORY argument analyzes the full package and
    filters (the in-package file rationale): contexts rooted outside
    the subtree still reach its state. The Counter.value waiver's
    finding must name the rank-writer context (rooted in
    utils/snapshot.py, OUTSIDE obs/) — an isolated-subtree analysis
    could never see it."""
    target = os.path.join(lint_mod.package_root(), "obs")
    rc = analysis_main(["--lint-only", target, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0, out["findings"]
    ptr = [w["finding"] for w in out["waived"]
           if w["finding"]["rule"].startswith("PTR")]
    assert ptr and all(f["path"].startswith("obs/") for f in ptr)
    counter = next(f for f in ptr if f["snippet"] == "Counter.value")
    assert "rank-writer" in counter["message"]


def test_prefix_drain_handler_fixture_fires_ptr003(tmp_path):
    """Provenance, like the PTL001 ell-deal fixture: the pre-ISSUE-14
    GracefulDrain._handler performed telemetry IN HANDLER CONTEXT
    (stderr write via obs_log, registry get-or-create). The replica
    must trip PTR003 through the injectable-install idiom; the shipped
    jobs.py (flags only, telemetry deferred to the next safe point) is
    covered by the clean-tree gate below."""
    bad = _write(tmp_path, "drain_old.py", """
        import signal
        import sys

        class Drain:
            def __init__(self, install=signal.signal):
                self._install = install
                self.requested = False
                self.signum = None

            def __enter__(self):
                self._prev = self._install(signal.SIGTERM, self._handler)
                return self

            def _handler(self, signum, frame):
                self.requested = True
                self.signum = int(signum)
                sys.stderr.write("draining\\n")  # pre-fix telemetry
    """)
    findings = conc_mod.analyze_file(bad)
    assert "PTR003" in _rules_of(findings), [f.render() for f in findings]


# -- whole-package analysis: contexts, roots, the clean gate ----------------


@pytest.fixture(scope="module")
def package_program():
    return conc_mod.build_package_program()


def test_thread_roots_discovered_with_labels(package_program):
    labels = {ts.label for ts in package_program.thread_sites}
    assert {"rank-writer", "pagerank-stall-watchdog",
            "pagerank-metrics-http", "pagerank-deadline-dispatch",
            "pagerank-liveness-probe"} <= labels


def test_signal_root_is_graceful_drain_handler(package_program):
    assert ("signal:GracefulDrain._handler",
            "jobs.py::GracefulDrain._handler") in \
        package_program.signal_roots


def test_context_inference_reaches_shared_infrastructure(package_program):
    ctx = package_program.contexts
    # The watchdog's fire path registers counters: the registry's
    # get-or-create runs in watchdog context (the PTR001 class the
    # registry lock now guards).
    assert "pagerank-stall-watchdog" in \
        ctx["obs/metrics.py::MetricsRegistry._get"]
    # The HTTP handler renders through the exporter closure alias.
    assert "pagerank-metrics-http" in ctx["obs/live.py::Handler.do_GET"]
    # The rank-writer worker reaches the SinkGuard policy.
    assert "rank-writer" in ctx["utils/snapshot.py::SinkGuard.__call__"]
    # The signal context is confined to the handler after the fix —
    # obs_log's stderr funnel is NOT handler-reachable anymore.
    assert not any(c.startswith("signal:")
                   for c in ctx["obs/log.py::_emit"])


def test_handler_roots_shared_source_of_truth():
    """The ISSUE-14 satellite: PTL008's scope and PTR003's root
    discovery read ONE source of truth (analysis/roots.py), so moving
    GracefulDrain cannot silently split the two rules' views."""
    assert roots_mod.HANDLER_OWNER_MODULES == ("jobs.py", "cli.py")
    for rel in roots_mod.HANDLER_OWNER_MODULES:
        assert lint_mod._scope_match("handler_free", rel) is False
    assert lint_mod._scope_match("handler_free", "utils/snapshot.py")
    assert lint_mod._scope_match("handler_free", "parallel/elastic.py")
    # The real jobs.py installation (the injectable-install idiom) is
    # discovered by the shared walker.
    path = os.path.join(lint_mod.package_root(), "jobs.py")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    installs = list(roots_mod.iter_handler_installs(tree))
    assert any(cls == "GracefulDrain" for _call, _h, cls in installs)


def test_package_tree_has_zero_unwaived_ptr_findings():
    """The acceptance gate's AST half: the shipped tree is PTR-clean
    modulo the reasoned allowlist entries — and the waivers that ARE
    there all match live findings (no stale debt)."""
    findings = conc_mod.analyze_package()
    allow = os.path.join(lint_mod.package_root(), "analysis",
                         "allowlist.txt")
    active, waived = split_allowlisted(findings, load_allowlist(allow))
    assert [f.render() for f in active] == []
    assert any(f.rule.startswith("PTR") for f, _w in waived)


def test_fixed_defects_stay_fixed(package_program):
    """The three audit fixes, pinned structurally: (1) the registry map
    and histogram internals are lock-guarded; (2) the drain handler's
    closure performs no telemetry; (3) probe_liveness takes an
    injectable clock."""
    findings = conc_mod.analyze_package()
    assert not any(f.rule == "PTR003" for f in findings), \
        [f.render() for f in findings if f.rule == "PTR003"]
    assert not any(f.rule == "PTR006" for f in findings), \
        [f.render() for f in findings if f.rule == "PTR006"]
    assert not any(f.snippet == "MetricsRegistry._metrics"
                   for f in findings)


# -- interleaving replays (testing/schedules.py) ----------------------------


def test_same_seed_same_schedule_bit_for_bit():
    def build(sched):
        def a():
            for i in range(5):
                yield f"a{i}"

        def b():
            for i in range(3):
                yield f"b{i}"

        sched.spawn("a", a())
        sched.spawn("b", b())

    logs = [schedules.replay(seed=11, build=build).log for _ in range(2)]
    assert logs[0] == logs[1]
    other = schedules.replay(seed=12, build=build).log
    assert other != logs[0]  # a different seed permutes the schedule


_PREFIX_MSG = "signal %d: draining"


def _prefix_handler(drain, signum):
    """The pre-ISSUE-14 GracefulDrain._handler body, verbatim in
    behavior: flag sets PLUS in-handler telemetry (registry
    get-or-create + obs_log.warn -> tracer.add_event -> tracer lock)."""
    if drain.requested:
        return
    drain.requested = True
    drain.signum = int(signum)
    drain._t_request = drain._clock()
    obs_metrics.counter(
        "job.drain_requests",
        "graceful-drain requests received (first SIGTERM/SIGINT)",
    ).inc()
    from pagerank_tpu.obs import log as obs_log

    obs_log.warn(_PREFIX_MSG % signum)


def _drain_replay(seed, deliver_factory):
    """One seeded schedule interleaving a traced main loop with a
    signal delivery. Returns (scheduler, tracer_lock, results,
    deadlock: bool)."""
    clock = schedules.VirtualClock()
    results = {"interrupted": False, "held_at_delivery": None}
    sched = schedules.InterleavingScheduler(seed=seed, clock=clock)
    tracer = obs_trace.Tracer()
    lock = schedules.TrackedLock("tracer._lock", sched)
    tracer._lock = lock
    obs_metrics.get_registry().reset()
    obs_trace.enable_tracing(tracer)
    drain = jobs.GracefulDrain(
        deadline_s=5.0, install=lambda s, h: None,
        hard_exit=lambda code: None, clock=clock,
    )
    deliver = deliver_factory(drain)

    def main_task():
        # The tracer's add_event/_pop critical section: exactly where
        # the main thread holds tracer._lock — a signal can land on
        # any bytecode inside it.
        with lock:
            yield "tracer-lock-held"
        yield "lock-released"
        try:
            drain.check("solve")
        except jobs.DrainInterrupt:
            results["interrupted"] = True
        yield "checked"

    def signal_task():
        yield "pre-delivery"
        results["held_at_delivery"] = lock.holder is not None
        deliver()
        yield "delivered"

    sched.spawn("main", main_task())
    sched.spawn("signal", signal_task())
    deadlock = False
    try:
        sched.run()
    except schedules.DeadlockDetected:
        deadlock = True
    finally:
        obs_trace.disable_tracing()
    return sched, lock, results, deadlock


SEEDS = range(40)


def test_replay_reproduces_the_prefix_handler_deadlock():
    """The race the pass found and the fix removed, REPRODUCED: under
    schedules where the signal lands while the main thread holds the
    tracer lock, the pre-fix handler re-acquires it on the same OS
    thread — DeadlockDetected, deterministically, same seeds every
    run."""
    def deliver_factory(drain):
        return lambda: _prefix_handler(drain, signal.SIGTERM)

    outcomes = {}
    for seed in SEEDS:
        _s, _l, results, deadlock = _drain_replay(seed, deliver_factory)
        outcomes[seed] = (deadlock, results["held_at_delivery"])
        if deadlock:
            assert results["held_at_delivery"], (
                "a deadlock requires delivery inside the held region")
    deadlocked = {s for s, (d, _h) in outcomes.items() if d}
    assert deadlocked, "no sampled schedule hit the held-lock window"
    # Bit-for-bit: the same seeds deadlock on a second pass.
    again = {s for s in SEEDS
             if _drain_replay(s, deliver_factory)[3]}
    assert again == deadlocked


def test_fixed_handler_survives_all_schedules():
    """The fix, pinned: the shipped GracefulDrain._handler sets flags
    only — under the SAME schedules (including ones delivering inside
    the held-lock window) no lock is ever touched from the signal
    actor, the drain is honored at the next safe point, and the
    deferred telemetry is emitted exactly once."""
    def deliver_factory(drain):
        return lambda: drain._handler(signal.SIGTERM, None)

    hit_held_window = False
    for seed in SEEDS:
        sched, lock, results, deadlock = _drain_replay(
            seed, deliver_factory)
        assert not deadlock, f"seed {seed} deadlocked with the FIXED handler"
        assert "signal" not in lock.acquirers(), (
            f"seed {seed}: the handler touched the tracer lock")
        hit_held_window |= bool(results["held_at_delivery"])
        # Delivery before the check -> honored at that safe point with
        # the telemetry emitted there; delivery after -> honored at
        # the NEXT safe point (checked here post-run).
        if results["interrupted"]:
            snap = obs_metrics.get_registry().snapshot()
            assert snap["counters"]["job.drain_requests"] == 1
    assert hit_held_window, (
        "no sampled schedule exercised the dangerous window")


def test_waived_rescue_handshake_benign_under_all_schedules():
    """The allowlist's PTR001 waiver for StallWatchdog.rescue_requested
    names this test: under every sampled schedule of watchdog fires vs
    main-thread heartbeats/consumes, the one-shot handshake never
    double-consumes a fire and never leaves a request dangling."""
    for seed in range(25):
        clock = schedules.VirtualClock()
        wd = obs_live.StallWatchdog(
            timeout_s=5.0, action="rescue", clock=clock,
            interrupt=lambda: None, device_source=lambda: [],
        )
        consumed = {"n": 0}

        def watchdog_task():
            for _ in range(6):
                clock.advance(3.0)
                wd.check()
                yield "poll"

        def solve_task():
            rng = random.Random(seed * 7 + 1)
            for i in range(6):
                if rng.random() < 0.5:
                    wd.heartbeat(i)
                    yield "heartbeat"
                if wd.consume_rescue():
                    consumed["n"] += 1
                yield "consume"

        sched = schedules.InterleavingScheduler(seed=seed, clock=clock)
        sched.spawn("watchdog", watchdog_task())
        sched.spawn("solve", solve_task())
        sched.run()
        if wd.consume_rescue():  # final drain of a dangling request
            consumed["n"] += 1
        assert consumed["n"] <= wd.stalls, (
            f"seed {seed}: consumed more rescues than fires")
        assert not wd.rescue_requested


# -- the registry/exporter fix under real threads ---------------------------


def test_exporter_render_concurrent_with_recording():
    """Regression for the PTR001 finding the audit surfaced on
    MetricsRegistry._metrics: the exporter thread renders while other
    contexts register and record. With the registry map and histogram
    buckets lock-guarded this ALWAYS passes; pre-fix the scrape could
    die iterating a dict mid-insert."""
    reg = obs_metrics.MetricsRegistry()
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        try:
            while not stop.is_set():
                reg.histogram(f"h.{i % 211}", "hammer").record(i % 4096)
                reg.counter(f"c.{i % 97}", "hammer").inc()
                reg.gauge(f"g.{i % 53}", "hammer").set(i)
                i += 1
        except Exception as e:  # pragma: no cover - the regression
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                obs_live.render_prometheus(reg)
                reg.snapshot()
        except Exception as e:  # pragma: no cover - the regression
            errors.append(e)

    threads = [threading.Thread(target=writer, daemon=True),
               threading.Thread(target=reader, daemon=True)]
    for t in threads:
        t.start()
    time.sleep(0.4)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert errors == []
    # The render still strict-parses as exposition format afterwards.
    text = obs_live.render_prometheus(reg)
    for line in text.splitlines():
        assert line.startswith("#") or " " in line
