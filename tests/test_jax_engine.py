"""JaxTpuEngine vs the float64 CPU oracle (SURVEY.md §4: single
dense-vs-sparse update-step equivalence + the L1 acceptance gate)."""

import numpy as np
import pytest

from pagerank_tpu import (
    JaxTpuEngine,
    PageRankConfig,
    ReferenceCpuEngine,
    build_graph,
)
from pagerank_tpu.ingest import records_to_graph
from tests.test_cpu_engine import TOY_RECORDS


def random_graph(rng, n=200, e=1500):
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


def test_toy_matches_oracle_f64_exact():
    graph, _ = records_to_graph(TOY_RECORDS)
    cfg = PageRankConfig(num_iters=10, dtype="float64", accum_dtype="float64")
    r_jax = JaxTpuEngine(cfg.replace(num_devices=1)).build(graph).run()
    r_cpu = ReferenceCpuEngine(cfg).build(graph).run()
    np.testing.assert_allclose(r_jax, r_cpu, rtol=0, atol=1e-13)


@pytest.mark.parametrize("semantics", ["reference", "textbook"])
def test_random_graph_matches_oracle(semantics):
    rng = np.random.default_rng(7)
    graph = random_graph(rng)
    cfg = PageRankConfig(
        num_iters=15, semantics=semantics, dtype="float64", accum_dtype="float64"
    )
    r_jax = JaxTpuEngine(cfg.replace(num_devices=1)).build(graph).run()
    r_cpu = ReferenceCpuEngine(cfg).build(graph).run()
    np.testing.assert_allclose(r_jax, r_cpu, rtol=0, atol=1e-12)


def test_float32_within_tolerance_of_f64_oracle():
    rng = np.random.default_rng(3)
    graph = random_graph(rng, n=500, e=4000)
    cfg = PageRankConfig(num_iters=20)
    r_jax = JaxTpuEngine(cfg).build(graph).run()  # f32, all fake devices
    r_cpu = ReferenceCpuEngine(cfg).build(graph).run()
    # N-scaled ranks are O(1); elementwise f32 tolerance.
    np.testing.assert_allclose(r_jax, r_cpu, rtol=0, atol=5e-4)
    assert np.abs(r_jax - r_cpu).sum() / graph.n < 1e-4


def test_step_reports_dangling_mass_and_delta():
    graph, _ = records_to_graph(TOY_RECORDS)
    eng = JaxTpuEngine(PageRankConfig(dtype="float64", accum_dtype="float64")).build(graph)
    info = eng.step()
    # "d" is crawled, so the repair pass empties dangUrls on this graph.
    assert info["dangling_mass"] == pytest.approx(0.0)
    assert info["l1_delta"] > 0

    # An uncrawled target does carry mass.
    g2, _ = records_to_graph([("a", ["x"]), ("b", ["a"])])
    e2 = JaxTpuEngine(PageRankConfig(dtype="float64", accum_dtype="float64")).build(g2)
    assert e2.step()["dangling_mass"] == pytest.approx(1.0)  # r0[x] = 1


def test_run_fast_equals_stepwise():
    graph, _ = records_to_graph(TOY_RECORDS)
    cfg = PageRankConfig(num_iters=10, dtype="float64", accum_dtype="float64")
    r1 = JaxTpuEngine(cfg).build(graph).run()
    r2 = JaxTpuEngine(cfg).build(graph).run_fast()
    np.testing.assert_array_equal(r1, r2)


def test_set_ranks_resume_midway():
    graph, _ = records_to_graph(TOY_RECORDS)
    cfg = PageRankConfig(num_iters=10, dtype="float64", accum_dtype="float64")
    full = JaxTpuEngine(cfg).build(graph).run()

    first = JaxTpuEngine(cfg).build(graph)
    first.run(num_iters=4)
    snap = first.ranks()

    resumed = JaxTpuEngine(cfg).build(graph)
    resumed.set_ranks(snap, iteration=4)
    r = resumed.run()
    np.testing.assert_allclose(r, full, rtol=0, atol=1e-13)


def test_run_fused_equals_stepwise():
    graph, _ = records_to_graph(TOY_RECORDS)
    cfg = PageRankConfig(num_iters=10, dtype="float64", accum_dtype="float64")
    r1 = JaxTpuEngine(cfg).build(graph).run_fast()
    eng = JaxTpuEngine(cfg).build(graph)
    r2 = eng.run_fused()
    # Same math, but the scan body and the standalone step are separate
    # XLA programs — last-ulp differences are allowed.
    np.testing.assert_allclose(r1, r2, rtol=0, atol=1e-13)
    assert eng.iteration == 10
    # per-iteration traces captured as device arrays
    m = eng.last_run_metrics
    assert m["l1_delta"].shape == (10,)
    assert m["dangling_mass"].shape == (10,)
    # resuming mid-way fuses only the remainder
    eng2 = JaxTpuEngine(cfg).build(graph)
    eng2.run(num_iters=4)
    r3 = eng2.run_fused()
    np.testing.assert_allclose(r3, r1, rtol=0, atol=1e-13)
    # idempotent once complete
    np.testing.assert_array_equal(eng.run_fused(), r2)  # no-op: already complete


def test_run_fused_zero_iters():
    graph, _ = records_to_graph(TOY_RECORDS)
    cfg = PageRankConfig(num_iters=0, dtype="float64", accum_dtype="float64")
    eng = JaxTpuEngine(cfg).build(graph)
    assert eng.prepare_fused() == 0
    r = eng.run_fused()
    assert r.shape == (graph.n,)
    assert eng.last_run_metrics["l1_delta"].shape == (0,)


def test_run_fused_tol_matches_host_early_stop():
    graph, _ = records_to_graph(TOY_RECORDS)
    cfg = PageRankConfig(num_iters=50, dtype="float64", accum_dtype="float64",
                         tol=1e-8)
    host = JaxTpuEngine(cfg).build(graph)
    r_host = host.run()  # host-checked early stop
    fused = JaxTpuEngine(cfg).build(graph)
    r_fused = fused.run_fused_tol()
    # Host checks tol AFTER the step it just ran; the device cond checks
    # BEFORE running another — identical stop iteration.
    assert fused.iteration == host.iteration
    np.testing.assert_allclose(r_fused, r_host, rtol=0, atol=1e-13)
    assert fused.iteration < 50  # actually stopped early
    assert fused.last_run_metrics["l1_delta"].shape == (1,)
    assert float(fused.last_run_metrics["l1_delta"][0]) <= 1e-8
    # budget exhaustion: loose budget, tight tol -> runs out of budget
    capped = JaxTpuEngine(cfg.replace(num_iters=3, tol=1e-30)).build(graph)
    capped.run_fused_tol()
    assert capped.iteration == 3
