"""Hardening: NaN sanitizer sweep and literal kill-and-resume fault
injection (SURVEY.md §5 "Race detection / sanitizers" and "Failure
detection / elastic recovery / fault injection").

The reference computes a transient ``rank/0 = Infinity`` it never emits
(Sparky.java:207, SURVEY §2a.6); this framework's prescaled formulation
must never manufacture a NaN/Inf at all — asserted here under
``jax_debug_nans``. Fault injection is the real thing: SIGKILL the CLI
mid-run, resume from the latest atomic snapshot, and land on the exact
ranks of an uninterrupted run.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

from pagerank_tpu import JaxTpuEngine, PageRankConfig
from pagerank_tpu.ingest import records_to_graph


def test_no_nans_under_debug_nans():
    """Both semantics modes, with dangling + linkless + uncrawled
    vertices present, run clean under the NaN sanitizer — the
    reference's transient inf (Sparky.java:207) has no analogue here."""
    records = [
        ("a", ["b", "c"]),
        ("b", ["a"]),
        ("c", []),          # crawled, linkless
        ("d", ["missing"]),  # uncrawled target
    ]
    graph, _ = records_to_graph(records)
    jax.config.update("jax_debug_nans", True)
    try:
        for semantics in ("reference", "textbook"):
            cfg = PageRankConfig(
                num_iters=8, semantics=semantics,
                dtype="float64", accum_dtype="float64",
            )
            r = JaxTpuEngine(cfg).build(graph).run_fast()
            assert np.isfinite(r).all()
    finally:
        jax.config.update("jax_debug_nans", False)


def _run_cli(args, env):
    return subprocess.run(
        [sys.executable, "-m", "pagerank_tpu.cli", *args],
        capture_output=True, text=True, env=env,
    )


@pytest.mark.parametrize("fused", [False, True], ids=["stepwise", "fused"])
def test_sigkill_mid_run_then_resume(tmp_path, fused):
    """Fault injection per SURVEY §5: kill -9 the process mid-run; the
    atomic per-iteration snapshots allow an exact resume. Runs in both
    dispatch modes — fused uses chunked dispatches between snapshot
    points (run_fused_chunked), which must checkpoint and resume exactly
    like the stepwise loop."""
    rng = np.random.default_rng(23)
    edges = tmp_path / "e.txt"
    edges.write_text(
        "".join(f"{s} {d}\n" for s, d in
                zip(rng.integers(0, 3000, 30000),
                    rng.integers(0, 3000, 30000)))
    )
    env = {
        **{k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")},
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
               if p]  # an empty entry would put the cwd on sys.path
        ),
    }
    snap_dir = tmp_path / "snaps"
    base = ["--input", str(edges), "--iters", "40",
            "--snapshot-dir", str(snap_dir), "--dtype", "float64",
            "--accum-dtype", "float64", "--log-every", "0"]
    if fused:
        base.append("--fused")

    victim = subprocess.Popen(
        [sys.executable, "-m", "pagerank_tpu.cli", *base],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        # Kill as soon as the FIRST completed snapshot lands — the
        # earlier the kill, the further the victim is from done.
        deadline = time.time() + 120
        while time.time() < deadline:
            done = [n for n in os.listdir(snap_dir)] if snap_dir.exists() else []
            if any(n.endswith(".npz") and not n.endswith(".tmp.npz")
                   for n in done):
                break
            if victim.poll() is not None:
                pytest.fail("victim finished before it could be killed; "
                            "raise --iters")
            time.sleep(0.02)
        else:
            pytest.fail("no snapshots appeared within 120s")
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=60)
    finally:
        if victim.poll() is None:
            victim.kill()
            victim.wait()

    # Resume to completion — and prove the kill actually interrupted
    # the run (a vacuous resume-from-40 would test nothing).
    r = _run_cli(base + ["--resume"], env)
    assert r.returncode == 0, r.stderr[-500:]
    import re

    m = re.search(r"resumed from iteration (\d+)", r.stderr)
    assert m, r.stderr[-300:]
    assert int(m.group(1)) < 40, (
        f"victim completed all 40 iterations before SIGKILL landed "
        f"(resumed from {m.group(1)}); enlarge the graph"
    )

    # Uninterrupted control run.
    ctrl_dir = tmp_path / "ctrl"
    r2 = _run_cli(["--input", str(edges), "--iters", "40",
                   "--snapshot-dir", str(ctrl_dir), "--dtype", "float64",
                   "--accum-dtype", "float64", "--log-every", "0"], env)
    assert r2.returncode == 0, r2.stderr[-500:]

    a = np.load(snap_dir / "ranks_iter40.npz")["ranks"]
    b = np.load(ctrl_dir / "ranks_iter40.npz")["ranks"]
    np.testing.assert_array_equal(a, b)


def test_resume_skips_corrupted_latest_snapshot(tmp_path, capsys):
    """Chaos variant of kill-and-resume (ISSUE 3): the newest snapshot
    is CORRUPTED after the 'crash'. --resume must detect it via the
    content checksum, fall back to the newest valid iteration, and
    still land on the exact ranks of an uninterrupted run."""
    import warnings

    from pagerank_tpu.cli import main

    rng = np.random.default_rng(7)
    edges = tmp_path / "e.txt"
    edges.write_text(
        "".join(f"{s} {d}\n" for s, d in
                zip(rng.integers(0, 200, 1500), rng.integers(0, 200, 1500)))
    )
    sd = tmp_path / "snaps"
    base = ["--input", str(edges), "--dtype", "float64",
            "--accum-dtype", "float64", "--log-every", "0"]
    # phase 1: 5 iterations, then "crash" and corrupt the newest
    assert main(base + ["--iters", "5", "--snapshot-dir", str(sd)]) == 0
    raw = (sd / "ranks_iter5.npz").read_bytes()
    (sd / "ranks_iter5.npz").write_bytes(raw[: len(raw) // 2])
    # phase 2: resume to 8 — must fall back to iteration 4
    capsys.readouterr()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        assert main(base + ["--iters", "8", "--snapshot-dir", str(sd),
                            "--resume"]) == 0
    assert "resumed from iteration 4" in capsys.readouterr().err
    ctrl = tmp_path / "ctrl"
    assert main(base + ["--iters", "8", "--snapshot-dir", str(ctrl)]) == 0
    a = np.load(sd / "ranks_iter8.npz")["ranks"]
    b = np.load(ctrl / "ranks_iter8.npz")["ranks"]
    np.testing.assert_array_equal(a, b)
