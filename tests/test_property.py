"""Property-based fuzzing (hypothesis): structural invariants of the
graph build and full-pipeline parity between the vectorized engines and
the dict-based RDD transliteration, on arbitrary generated inputs —
SURVEY.md §4's oracle strategy pushed past hand-picked cases."""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property fuzzing needs the hypothesis package"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from pagerank_tpu import (
    JaxTpuEngine,
    PageRankConfig,
    ReferenceCpuEngine,
    build_graph,
)
from pagerank_tpu.graph import inv_out_degree
from pagerank_tpu.ingest import records_to_graph
from tests.oracle_rdd import sparky_pagerank

edge_lists = st.integers(2, 60).flatmap(
    lambda n: st.lists(
        st.tuples(st.integers(0, n - 1), st.integers(0, n - 1)),
        min_size=1, max_size=300,
    ).map(lambda es: (n, es))
)


@given(edge_lists)
@settings(max_examples=40, deadline=None)
def test_graph_build_invariants(data):
    n, es = data
    src = np.array([e[0] for e in es])
    dst = np.array([e[1] for e in es])
    g = build_graph(src, dst, n=n)
    # dedup: unique edge count
    assert g.num_edges == len(set(es))
    # out_degree counts unique targets per source (quirk §2a.5)
    assert int(g.out_degree.sum()) == g.num_edges
    # dst-major packing: sorted by (dst, src)
    keys = g.dst.astype(np.int64) * n + g.src
    assert (np.diff(keys) > 0).all()
    # masks: edge-list inputs -> dangling == (out_degree == 0)
    np.testing.assert_array_equal(g.dangling_mask, g.out_degree == 0)
    in_deg = np.bincount(g.dst, minlength=n)
    np.testing.assert_array_equal(g.zero_in_mask, in_deg == 0)
    # normalization: 1/deg with 0-for-0
    inv = inv_out_degree(g.out_degree)
    assert np.isfinite(inv).all()
    assert (inv[g.out_degree == 0] == 0).all()


crawl_records = st.integers(2, 20).flatmap(
    lambda n: st.lists(
        st.tuples(
            st.integers(0, n - 1),
            st.lists(st.integers(0, n + 3), max_size=6),  # may hit uncrawled ids
        ),
        min_size=1, max_size=20, unique_by=lambda t: t[0],
    )
)


@given(crawl_records)
@settings(max_examples=25, deadline=None)
def test_engines_match_rdd_oracle_on_random_crawls(recs):
    records = [(f"u{i}", [f"u{t}" for t in ts]) for i, ts in recs]
    graph, ids = records_to_graph(records)
    cfg = PageRankConfig(num_iters=7, dtype="float64", accum_dtype="float64")

    expected, _, _, _ = sparky_pagerank(records, num_iters=7)
    want = np.array([expected[name] for name in ids.names])

    r_cpu = ReferenceCpuEngine(cfg).build(graph).run()
    np.testing.assert_allclose(r_cpu, want, rtol=0, atol=1e-9)

    r_jax = JaxTpuEngine(cfg).build(graph).run_fast()
    np.testing.assert_allclose(r_jax, want, rtol=0, atol=1e-9)


@given(crawl_records)
@settings(max_examples=15, deadline=None)
def test_device_build_matches_host_on_random_crawls(recs):
    """The on-device build fed raw crawl arrays (the --device-build
    path: records_to_arrays + dangling override) must agree with the
    host build + RDD oracle on arbitrary crawl shapes — uncrawled
    targets, crawled linkless pages, duplicate edges, self-loops."""
    from pagerank_tpu.ingest import records_to_arrays
    from pagerank_tpu.ops import device_build as db

    records = [(f"u{i}", [f"u{t}" for t in ts]) for i, ts in recs]
    src, dst, crawled, ids = records_to_arrays(records)
    cfg = PageRankConfig(num_iters=7, dtype="float64", accum_dtype="float64")

    dg = db.build_ell_device(src, dst, n=len(ids), weight_dtype=np.float64,
                             dangling_mask=~crawled)
    r_dev = JaxTpuEngine(cfg).build_device(dg).run()

    expected, _, _, _ = sparky_pagerank(records, num_iters=7)
    want = np.array([expected[name] for name in ids.names])
    np.testing.assert_allclose(r_dev, want, rtol=0, atol=1e-9)


@given(
    st.integers(1, 2000),
    st.integers(1, 16),
    st.booleans(),
)
@settings(max_examples=60, deadline=None)
def test_deal_block_order_always_valid(n, ndev, weighted):
    """deal_block_order (vs_bounded's LPT dst deal) yields a valid
    block permutation for ANY (n, ndev, weights): injective, filled
    slots contiguous from 0, partial block globally last, per-device
    assignment within capacity."""
    from pagerank_tpu.ops import ell as ell_lib

    n_padded = -(-n // 128) * 128
    nb_fill = n_padded // 128
    w = None
    if weighted:
        rng = np.random.default_rng(n * 31 + ndev)
        w = rng.integers(1, 1000, nb_fill).astype(float)
    new_of_old = ell_lib.deal_block_order(n, n_padded, ndev, weights=w)
    assert len(new_of_old) == nb_fill
    assert sorted(new_of_old) == list(range(nb_fill))  # bijective+packed
    nbd = -(-nb_fill // ndev)
    assert max(new_of_old) < nbd * ndev
    if n % 128:
        assert new_of_old[-1] == nb_fill - 1
    # per-device counts never exceed the slot capacity
    devs = np.asarray(new_of_old) // nbd
    assert np.bincount(devs, minlength=ndev).max() <= nbd
    # the dealt vertex order used by the packer is a dense permutation
    ids = np.arange(n, dtype=np.int64)
    new_pos = (np.asarray(new_of_old)[ids >> 7] << 7) | (ids & 127)
    assert sorted(new_pos) == list(range(n))
