"""On-device graph build (ops/device_build.py) vs the host builder
(graph.py + ops/ell.py): same semantics, slot-for-slot where defined."""

import numpy as np
import pytest

import jax

from pagerank_tpu import JaxTpuEngine, PageRankConfig, ReferenceCpuEngine, build_graph
from pagerank_tpu.ops import device_build as db
from pagerank_tpu.ops import ell as ell_lib


def _host_graph_and_pack(src, dst, n):
    g = build_graph(np.asarray(src), np.asarray(dst), n=n)
    return g, ell_lib.ell_pack(g)


def test_slot_parity_with_host_pack_no_dups():
    rng = np.random.default_rng(3)
    n, e = 300, 2000
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    # Pre-dedup so the host and device packers see identical edge sets
    # (the device packer keeps duplicate slots with weight 0 instead of
    # compacting — layout differs, result doesn't; see below).
    key = src.astype(np.int64) * n + dst
    key = np.unique(key)
    src_u = (key // n).astype(np.int32)
    dst_u = (key % n).astype(np.int32)

    g, pack = _host_graph_and_pack(src_u, dst_u, n)
    dg = db.build_ell_device(src_u, dst_u, n)

    assert dg.num_edges == pack.num_real_edges == len(key)
    np.testing.assert_array_equal(np.asarray(dg.perm), pack.perm)
    assert dg.num_rows == pack.num_rows
    np.testing.assert_array_equal(np.asarray(dg.row_block), pack.row_block)
    np.testing.assert_array_equal(np.asarray(dg.src), pack.src)
    np.testing.assert_allclose(
        np.asarray(dg.weight), pack.weight.astype(np.float32), rtol=0, atol=0
    )
    np.testing.assert_array_equal(np.asarray(dg.dangling_mask), g.dangling_mask)
    np.testing.assert_array_equal(np.asarray(dg.zero_in_mask), g.zero_in_mask)


@pytest.mark.parametrize("semantics", ["reference", "textbook"])
def test_engine_from_device_build_matches_oracle(semantics):
    rng = np.random.default_rng(11)
    n, e = 257, 3000  # non-multiple of 128; duplicates present
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)

    dg = db.build_ell_device(src, dst, n, weight_dtype=np.float64)
    cfg = PageRankConfig(
        num_iters=12, semantics=semantics, dtype="float64", accum_dtype="float64"
    )
    eng = JaxTpuEngine(cfg.replace(num_devices=1)).build_device(dg)
    r_dev = eng.run()

    g = build_graph(src, dst, n=n)
    r_cpu = ReferenceCpuEngine(cfg).build(g).run()
    np.testing.assert_allclose(r_dev, r_cpu, rtol=0, atol=1e-12)


def test_device_build_dangling_mask_override():
    """Crawl semantics on the device build: the dangling mask override
    (uncrawled targets only, SURVEY §2a.3) must reach the engine and
    change the result exactly as the host build's override does —
    including a vertex with out_degree == 0 that is NOT dangling."""
    rng = np.random.default_rng(5)
    n, e = 130, 700
    src = rng.integers(0, n // 2, e).astype(np.int32)  # upper half: sinks
    dst = rng.integers(0, n, e).astype(np.int32)
    crawled = np.zeros(n, bool)
    crawled[: n // 2 + 7] = True  # some sinks crawled-but-linkless
    dangling = ~crawled

    cfg = PageRankConfig(num_iters=10, dtype="float64",
                         accum_dtype="float64", num_devices=1)
    dg = db.build_ell_device(src, dst, n, weight_dtype=np.float64,
                             dangling_mask=dangling)
    r_dev = JaxTpuEngine(cfg).build_device(dg).run()

    g = build_graph(src, dst, n=n, dangling_mask=dangling)
    r_host = JaxTpuEngine(cfg).build(g).run()
    r_cpu = ReferenceCpuEngine(cfg).build(g).run()
    np.testing.assert_allclose(r_dev, r_host, rtol=0, atol=1e-12)
    np.testing.assert_allclose(r_dev, r_cpu, rtol=0, atol=1e-12)
    # the default mask differs (out_degree==0 would include the crawled
    # linkless sinks) — guard that the override actually changed it
    dg_default = db.build_ell_device(src, dst, n, weight_dtype=np.float64)
    r_default = JaxTpuEngine(cfg).build_device(dg_default).run()
    assert np.abs(r_default - r_dev).max() > 1e-6


def test_device_build_sharded_runs():
    rng = np.random.default_rng(5)
    n, e = 512, 4000
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    dg = db.build_ell_device(src, dst, n)
    cfg = PageRankConfig(num_iters=5, num_devices=8)
    eng = JaxTpuEngine(cfg).build_device(dg)
    r8 = eng.run()

    g = build_graph(src, dst, n=n)
    r1 = JaxTpuEngine(cfg.replace(num_devices=1)).build(g).run()
    np.testing.assert_allclose(r8, r1, rtol=0, atol=1e-6)


def test_rmat_device_generator_shapes():
    src, dst = db.rmat_edges_device(8, edge_factor=4, seed=1)
    assert src.shape == dst.shape == (4 << 8,)
    s = np.asarray(src)
    d = np.asarray(dst)
    assert s.min() >= 0 and s.max() < 256
    assert d.min() >= 0 and d.max() < 256
    # Power-law-ish: some vertex ids repeat many times
    assert np.bincount(d, minlength=256).max() > 8


def test_engine_set_ranks_roundtrip_device_build():
    rng = np.random.default_rng(13)
    n, e = 200, 1000
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    dg = db.build_ell_device(src, dst, n)
    eng = JaxTpuEngine(PageRankConfig(num_devices=1)).build_device(dg)
    r = rng.random(n)
    eng.set_ranks(r, iteration=3)
    np.testing.assert_allclose(eng.ranks(), r, rtol=0, atol=1e-7)
    assert eng.iteration == 3


def test_grouped_device_build_matches_host_pack():
    # Device grouped pack must agree with the host grouped pack
    # slot-for-slot on a dedup'd edge list.
    rng = np.random.default_rng(21)
    n, e = 600, 4000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    g = build_graph(src, dst, n=n)
    host = ell_lib.ell_pack(g, group=8)
    dg = db.build_ell_device(
        jax.numpy.asarray(g.src), jax.numpy.asarray(g.dst), n=n, group=8
    )
    assert dg.group == 8
    np.testing.assert_array_equal(np.asarray(dg.src), host.src)
    np.testing.assert_array_equal(np.asarray(dg.row_block), host.row_block)


def test_grouped_device_engine_matches_oracle():
    rng = np.random.default_rng(23)
    n, e = 700, 6000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    g = build_graph(src, dst, n=n)
    cfg = PageRankConfig(
        num_iters=12, dtype="float64", accum_dtype="float64", lane_group=8
    )
    dg = db.build_ell_device(
        jax.numpy.asarray(src), jax.numpy.asarray(dst), n=n, group=8
    )
    eng = JaxTpuEngine(cfg).build_device(dg)
    eng.run()
    r = eng.ranks()
    ref = ReferenceCpuEngine(cfg).build(g)
    ref.run()
    np.testing.assert_allclose(r, ref.ranks(), rtol=0, atol=1e-12)


def test_striped_device_build_matches_host_pack():
    # Striped + grouped device pack vs the host striped pack,
    # slot-for-slot per stripe.
    rng = np.random.default_rng(31)
    n, e = 1000, 9000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    g = build_graph(src, dst, n=n)
    for group in (1, 8):
        host = ell_lib.ell_pack_striped(g, stripe_size=256, group=group)
        dg = db.build_ell_device(
            jax.numpy.asarray(g.src), jax.numpy.asarray(g.dst), n=n,
            group=group, stripe_size=256,
        )
        assert dg.stripe_size == 256
        assert len(dg.src) == host.n_stripes
        for s in range(host.n_stripes):
            np.testing.assert_array_equal(np.asarray(dg.src[s]), host.src[s])
            np.testing.assert_array_equal(
                np.asarray(dg.row_block[s]), host.row_block[s]
            )


def test_striped_device_engine_matches_oracle():
    rng = np.random.default_rng(33)
    n, e = 900, 8000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    g = build_graph(src, dst, n=n)
    cfg = PageRankConfig(
        num_iters=12, dtype="float64", accum_dtype="float64", lane_group=8
    )
    dg = db.build_ell_device(
        jax.numpy.asarray(src), jax.numpy.asarray(dst), n=n,
        group=8, stripe_size=256,
    )
    eng = JaxTpuEngine(cfg).build_device(dg)
    eng.run()
    ref = ReferenceCpuEngine(cfg).build(g)
    ref.run()
    np.testing.assert_allclose(eng.ranks(), ref.ranks(), rtol=0, atol=1e-12)


def test_presentinel_build_matches_weighted():
    # with_weights=False builds (sentinel-ized slot words, no weight
    # plane) must produce identical PageRank to the weighted build.
    rng = np.random.default_rng(41)
    n, e = 800, 7000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    cfg = PageRankConfig(
        num_iters=12, dtype="float64", accum_dtype="float64", lane_group=8
    )

    def run(with_weights, stripe):
        dg = db.build_ell_device(
            jax.numpy.asarray(src), jax.numpy.asarray(dst), n=n,
            group=8, stripe_size=stripe, with_weights=with_weights,
        )
        assert dg.presentinel == (not with_weights)
        eng = JaxTpuEngine(cfg).build_device(dg)
        eng.run()
        return eng.ranks()

    for stripe in (0, 256):
        np.testing.assert_allclose(
            run(False, stripe), run(True, stripe), rtol=0, atol=0
        )


def test_device_fingerprint_stable_and_discriminating():
    """fingerprint() must be identical for identical builds (incl.
    across the process-global x64 flip — the checksum dtype is pinned),
    and differ for a different graph."""
    rng = np.random.default_rng(5)
    n, e = 300, 2000
    # sources drawn below n-20: the top vertices are guaranteed sinks
    # (needed for the dangling-override case below)
    src, dst = rng.integers(0, n - 20, e), rng.integers(0, n, e)

    def build(s, d):
        return db.build_ell_device(
            jax.numpy.asarray(s), jax.numpy.asarray(d), n=n, group=4
        )

    fp1 = build(src, dst).fingerprint()
    jax.config.update("jax_enable_x64", True)  # one-way within a process
    fp2 = build(src, dst).fingerprint()
    assert fp1 == fp2 and fp1.startswith("dev-")
    assert build(dst, src).fingerprint() != fp1
    # Degree-PRESERVING rewire ({0->2, 1->3} vs {0->3, 1->2} shape):
    # identical degree vectors and perm, different adjacency — only a
    # slot-array checksum can tell these apart.
    a = db.build_ell_device(
        jax.numpy.asarray([0, 1]), jax.numpy.asarray([2, 3]), n=4
    ).fingerprint()
    b = db.build_ell_device(
        jax.numpy.asarray([0, 1]), jax.numpy.asarray([3, 2]), n=4
    ).fingerprint()
    assert a != b
    # The dangling-mask override is a semantic input in its own right
    # (crawl inputs: same edges, different crawled status) — snapshots
    # must not cross-validate between them, on EITHER build path. A
    # valid override is a SUBSET of the out-degree-0 vertices (a
    # crawled linkless page is not dangling), so build one that drops
    # half the default mask.
    from pagerank_tpu import build_graph

    hg1 = build_graph(src, dst, n=n)
    sinks = np.flatnonzero(hg1.out_degree == 0)
    assert len(sinks) >= 2, "test graph needs out-degree-0 vertices"
    mask = np.zeros(n, bool)
    mask[sinks[: len(sinks) // 2]] = True  # proper subset of the default
    fp_mask = db.build_ell_device(
        jax.numpy.asarray(src), jax.numpy.asarray(dst), n=n, group=4,
        dangling_mask=mask,
    ).fingerprint()
    assert fp_mask != fp1
    hg2 = build_graph(src, dst, n=n, dangling_mask=mask)
    assert hg1.fingerprint() != hg2.fingerprint()
