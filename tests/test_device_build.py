"""On-device graph build (ops/device_build.py) vs the host builder
(graph.py + ops/ell.py): same semantics, slot-for-slot where defined."""

import numpy as np
import pytest

import jax

from pagerank_tpu import JaxTpuEngine, PageRankConfig, ReferenceCpuEngine, build_graph
from pagerank_tpu.ops import device_build as db
from pagerank_tpu.ops import ell as ell_lib


def _host_graph_and_pack(src, dst, n):
    g = build_graph(np.asarray(src), np.asarray(dst), n=n)
    return g, ell_lib.ell_pack(g)


def test_slot_parity_with_host_pack_no_dups():
    rng = np.random.default_rng(3)
    n, e = 300, 2000
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    # Pre-dedup so the host and device packers see identical edge sets
    # (the device packer keeps duplicate slots with weight 0 instead of
    # compacting — layout differs, result doesn't; see below).
    key = src.astype(np.int64) * n + dst
    key = np.unique(key)
    src_u = (key // n).astype(np.int32)
    dst_u = (key % n).astype(np.int32)

    g, pack = _host_graph_and_pack(src_u, dst_u, n)
    dg = db.build_ell_device(src_u, dst_u, n)

    assert dg.num_edges == pack.num_real_edges == len(key)
    np.testing.assert_array_equal(np.asarray(dg.perm), pack.perm)
    assert dg.num_rows == pack.num_rows
    np.testing.assert_array_equal(np.asarray(dg.row_block), pack.row_block)
    np.testing.assert_array_equal(np.asarray(dg.src), pack.src)
    np.testing.assert_allclose(
        np.asarray(dg.weight), pack.weight.astype(np.float32), rtol=0, atol=0
    )
    np.testing.assert_array_equal(np.asarray(dg.dangling_mask), g.dangling_mask)
    np.testing.assert_array_equal(np.asarray(dg.zero_in_mask), g.zero_in_mask)


@pytest.mark.parametrize("semantics", ["reference", "textbook"])
def test_engine_from_device_build_matches_oracle(semantics):
    rng = np.random.default_rng(11)
    n, e = 257, 3000  # non-multiple of 128; duplicates present
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)

    dg = db.build_ell_device(src, dst, n, weight_dtype=np.float64)
    cfg = PageRankConfig(
        num_iters=12, semantics=semantics, dtype="float64", accum_dtype="float64"
    )
    eng = JaxTpuEngine(cfg.replace(num_devices=1)).build_device(dg)
    r_dev = eng.run()

    g = build_graph(src, dst, n=n)
    r_cpu = ReferenceCpuEngine(cfg).build(g).run()
    np.testing.assert_allclose(r_dev, r_cpu, rtol=0, atol=1e-12)


def test_device_build_dangling_mask_override():
    """Crawl semantics on the device build: the dangling mask override
    (uncrawled targets only, SURVEY §2a.3) must reach the engine and
    change the result exactly as the host build's override does —
    including a vertex with out_degree == 0 that is NOT dangling."""
    rng = np.random.default_rng(5)
    n, e = 130, 700
    src = rng.integers(0, n // 2, e).astype(np.int32)  # upper half: sinks
    dst = rng.integers(0, n, e).astype(np.int32)
    crawled = np.zeros(n, bool)
    crawled[: n // 2 + 7] = True  # some sinks crawled-but-linkless
    dangling = ~crawled

    cfg = PageRankConfig(num_iters=10, dtype="float64",
                         accum_dtype="float64", num_devices=1)
    dg = db.build_ell_device(src, dst, n, weight_dtype=np.float64,
                             dangling_mask=dangling)
    r_dev = JaxTpuEngine(cfg).build_device(dg).run()

    g = build_graph(src, dst, n=n, dangling_mask=dangling)
    r_host = JaxTpuEngine(cfg).build(g).run()
    r_cpu = ReferenceCpuEngine(cfg).build(g).run()
    np.testing.assert_allclose(r_dev, r_host, rtol=0, atol=1e-12)
    np.testing.assert_allclose(r_dev, r_cpu, rtol=0, atol=1e-12)
    # the default mask differs (out_degree==0 would include the crawled
    # linkless sinks) — guard that the override actually changed it
    dg_default = db.build_ell_device(src, dst, n, weight_dtype=np.float64)
    r_default = JaxTpuEngine(cfg).build_device(dg_default).run()
    assert np.abs(r_default - r_dev).max() > 1e-6


def test_device_build_sharded_runs():
    rng = np.random.default_rng(5)
    n, e = 512, 4000
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    dg = db.build_ell_device(src, dst, n)
    cfg = PageRankConfig(num_iters=5, num_devices=8)
    eng = JaxTpuEngine(cfg).build_device(dg)
    r8 = eng.run()

    g = build_graph(src, dst, n=n)
    r1 = JaxTpuEngine(cfg.replace(num_devices=1)).build(g).run()
    np.testing.assert_allclose(r8, r1, rtol=0, atol=1e-6)


def test_rmat_device_generator_shapes():
    src, dst = db.rmat_edges_device(8, edge_factor=4, seed=1)
    assert src.shape == dst.shape == (4 << 8,)
    s = np.asarray(src)
    d = np.asarray(dst)
    assert s.min() >= 0 and s.max() < 256
    assert d.min() >= 0 and d.max() < 256
    # Power-law-ish: some vertex ids repeat many times
    assert np.bincount(d, minlength=256).max() > 8


def test_engine_set_ranks_roundtrip_device_build():
    rng = np.random.default_rng(13)
    n, e = 200, 1000
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    dg = db.build_ell_device(src, dst, n)
    eng = JaxTpuEngine(PageRankConfig(num_devices=1)).build_device(dg)
    r = rng.random(n)
    eng.set_ranks(r, iteration=3)
    np.testing.assert_allclose(eng.ranks(), r, rtol=0, atol=1e-7)
    assert eng.iteration == 3


def test_grouped_device_build_matches_host_pack():
    # Device grouped pack must agree with the host grouped pack
    # slot-for-slot on a dedup'd edge list.
    rng = np.random.default_rng(21)
    n, e = 600, 4000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    g = build_graph(src, dst, n=n)
    host = ell_lib.ell_pack(g, group=8)
    dg = db.build_ell_device(
        jax.numpy.asarray(g.src), jax.numpy.asarray(g.dst), n=n, group=8
    )
    assert dg.group == 8
    np.testing.assert_array_equal(np.asarray(dg.src), host.src)
    np.testing.assert_array_equal(np.asarray(dg.row_block), host.row_block)


def test_grouped_device_engine_matches_oracle():
    rng = np.random.default_rng(23)
    n, e = 700, 6000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    g = build_graph(src, dst, n=n)
    cfg = PageRankConfig(
        num_iters=12, dtype="float64", accum_dtype="float64", lane_group=8
    )
    dg = db.build_ell_device(
        jax.numpy.asarray(src), jax.numpy.asarray(dst), n=n, group=8
    )
    eng = JaxTpuEngine(cfg).build_device(dg)
    eng.run()
    r = eng.ranks()
    ref = ReferenceCpuEngine(cfg).build(g)
    ref.run()
    np.testing.assert_allclose(r, ref.ranks(), rtol=0, atol=1e-12)


def test_striped_device_build_matches_host_pack():
    # Striped + grouped device pack vs the host striped pack,
    # slot-for-slot per stripe.
    rng = np.random.default_rng(31)
    n, e = 1000, 9000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    g = build_graph(src, dst, n=n)
    for group in (1, 8):
        host = ell_lib.ell_pack_striped(g, stripe_size=256, group=group)
        dg = db.build_ell_device(
            jax.numpy.asarray(g.src), jax.numpy.asarray(g.dst), n=n,
            group=group, stripe_size=256,
        )
        assert dg.stripe_size == 256
        assert len(dg.src) == host.n_stripes
        for s in range(host.n_stripes):
            np.testing.assert_array_equal(np.asarray(dg.src[s]), host.src[s])
            np.testing.assert_array_equal(
                np.asarray(dg.row_block[s]), host.row_block[s]
            )


def test_striped_device_engine_matches_oracle():
    rng = np.random.default_rng(33)
    n, e = 900, 8000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    g = build_graph(src, dst, n=n)
    cfg = PageRankConfig(
        num_iters=12, dtype="float64", accum_dtype="float64", lane_group=8
    )
    dg = db.build_ell_device(
        jax.numpy.asarray(src), jax.numpy.asarray(dst), n=n,
        group=8, stripe_size=256,
    )
    eng = JaxTpuEngine(cfg).build_device(dg)
    eng.run()
    ref = ReferenceCpuEngine(cfg).build(g)
    ref.run()
    np.testing.assert_allclose(eng.ranks(), ref.ranks(), rtol=0, atol=1e-12)


def test_presentinel_build_matches_weighted():
    # with_weights=False builds (sentinel-ized slot words, no weight
    # plane) must produce identical PageRank to the weighted build.
    rng = np.random.default_rng(41)
    n, e = 800, 7000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    cfg = PageRankConfig(
        num_iters=12, dtype="float64", accum_dtype="float64", lane_group=8
    )

    def run(with_weights, stripe):
        dg = db.build_ell_device(
            jax.numpy.asarray(src), jax.numpy.asarray(dst), n=n,
            group=8, stripe_size=stripe, with_weights=with_weights,
        )
        assert dg.presentinel == (not with_weights)
        eng = JaxTpuEngine(cfg).build_device(dg)
        eng.run()
        return eng.ranks()

    for stripe in (0, 256):
        np.testing.assert_allclose(
            run(False, stripe), run(True, stripe), rtol=0, atol=0
        )


def test_build_integer_planes_dtype_invariant():
    """ISSUE 2 parity gate: the f64-config and f32-config device builds
    must produce BIT-IDENTICAL integer planes (src slots, row_block —
    and therefore the row offsets it encodes — perm, out_degree) on the
    same fixed graph. The index path is pinned to 32-bit (contract
    PTC006), so the weight dtype — and the process-global x64 flip a
    64-bit config triggers — can only change the weight plane."""
    rng = np.random.default_rng(57)
    n, e = 700, 5000
    src = rng.integers(0, n, e).astype(np.int32)
    dst = rng.integers(0, n, e).astype(np.int32)
    for group, stripe in ((1, 0), (8, 256)):
        dgs = [
            db.build_ell_device(
                jax.numpy.asarray(src), jax.numpy.asarray(dst), n=n,
                weight_dtype=wdt, group=group, stripe_size=stripe,
            )
            for wdt in (np.float32, np.float64)
        ]
        a, b = dgs
        assert a.num_edges == b.num_edges
        np.testing.assert_array_equal(np.asarray(a.perm), np.asarray(b.perm))
        assert np.asarray(a.perm).dtype == np.int32
        np.testing.assert_array_equal(
            np.asarray(a.out_degree), np.asarray(b.out_degree)
        )
        assert np.asarray(a.out_degree).dtype == np.int32
        srcs_a = a.src if isinstance(a.src, list) else [a.src]
        srcs_b = b.src if isinstance(b.src, list) else [b.src]
        rbs_a = a.row_block if isinstance(a.row_block, list) else [a.row_block]
        rbs_b = b.row_block if isinstance(b.row_block, list) else [b.row_block]
        for sa, sb, ra, rb in zip(srcs_a, srcs_b, rbs_a, rbs_b):
            assert np.asarray(sa).dtype == np.int32
            assert np.asarray(ra).dtype == np.int32
            np.testing.assert_array_equal(np.asarray(sa), np.asarray(sb))
            np.testing.assert_array_equal(np.asarray(ra), np.asarray(rb))
        ws_a = a.weight if isinstance(a.weight, list) else [a.weight]
        ws_b = b.weight if isinstance(b.weight, list) else [b.weight]
        for wa, wb in zip(ws_a, ws_b):
            np.testing.assert_allclose(
                np.asarray(wa), np.asarray(wb).astype(np.float32),
                rtol=0, atol=0,
            )


def _twosort_reference(src, dst, n, group=1, stripe_size=0,
                       weight_dtype=np.float64):
    """Numpy oracle of the PRE-restage TWO-SORT device pipeline
    (_sort_dedup_degrees + _relabel_resort + slot coords as of PR 1):
    sort by (dst, src), dedup flags, UNIQUE-edge degrees, stable
    in-degree-descending relabel, (stripe, new_dst, new_src) re-sort,
    duplicate slots kept in place with weight 0. The restaged
    single-sort pipeline must reproduce this bit-for-bit whenever the
    relabel ordering agrees (always true on deduplicated inputs; the
    duplicate-laden caller below asserts the ordering precondition
    explicitly)."""
    LANES = 128
    n_padded = -(-n // LANES) * LANES
    sz = min(stripe_size, n_padded) if stripe_size else n_padded
    # -- sort 1: (dst, src); dedup flags; UNIQUE degrees
    o1 = np.lexsort((src, dst))
    s1, d1 = src[o1].astype(np.int64), dst[o1].astype(np.int64)
    uniq1 = np.r_[True, (s1[1:] != s1[:-1]) | (d1[1:] != d1[:-1])]
    out_degree = np.bincount(s1[uniq1], minlength=n).astype(np.int64)
    in_degree = np.bincount(d1[uniq1], minlength=n).astype(np.int64)
    # -- relabel by UNIQUE in-degree (the old pipeline's key)
    perm = np.argsort(-in_degree, kind="stable").astype(np.int32)
    inv = np.empty(n, np.int32)
    inv[perm] = np.arange(n, dtype=np.int32)
    ns1, nd1 = inv[s1].astype(np.int64), inv[d1].astype(np.int64)
    # -- sort 2: (stripe, new_dst, new_src)
    stripe_of = ns1 // sz
    o2 = np.lexsort((ns1, nd1, stripe_of))
    ns2, nd2, st2 = ns1[o2], nd1[o2], stripe_of[o2]
    # -- slot coordinates (same formulas as _slot_coords, in numpy)
    uniq2 = np.r_[True, (nd2[1:] != nd2[:-1]) | (ns2[1:] != ns2[:-1])]
    log2g = group.bit_length() - 1
    e_ = len(nd2)
    idx = np.arange(e_, dtype=np.int64)
    sb_key = st2 * n_padded + nd2
    grp = sb_key >> log2g
    is_start = np.r_[True, grp[1:] != grp[:-1]]
    first = np.maximum.accumulate(np.where(is_start, idx, 0))
    k = idx - first
    row = k >> log2g
    pos = ((nd2 % LANES) >> log2g) * group + (k & (group - 1))
    local = ns2 - st2 * sz
    word = (
        local if group == 1 else (local << log2g) | (nd2 & (group - 1))
    ).astype(np.int32)
    nb = n_padded // LANES
    n_stripes = -(-n_padded // sz)
    sb = st2 * nb + nd2 // LANES
    sb_rows = np.zeros(n_stripes * nb, np.int64)
    np.maximum.at(sb_rows, sb, row + 1)
    row_offset = np.r_[0, np.cumsum(sb_rows)]
    row_idx = row_offset[sb] + row
    rows_total = int(row_offset[-1])
    with np.errstate(divide="ignore"):
        inv_out = np.where(out_degree > 0, 1.0 / out_degree, 0.0)
    w_vals = np.where(uniq2, inv_out[s1[o2]], 0.0).astype(weight_dtype)
    src_slots = np.zeros((rows_total, LANES), np.int32)
    w_slots = np.zeros((rows_total, LANES), weight_dtype)
    src_slots[row_idx, pos] = word
    w_slots[row_idx, pos] = w_vals
    row_block = np.repeat(
        np.tile(np.arange(nb, dtype=np.int32), n_stripes), sb_rows
    )
    bounds = row_offset[::nb]
    return dict(
        perm=perm, out_degree=out_degree.astype(np.int32),
        in_degree=in_degree, num_edges=int(uniq2.sum()),
        src=[src_slots[lo:hi] for lo, hi in zip(bounds, bounds[1:])],
        weight=[w_slots[lo:hi] for lo, hi in zip(bounds, bounds[1:])],
        row_block=[row_block[lo:hi] for lo, hi in zip(bounds, bounds[1:])],
        stripe_bounds=bounds,
    )


@pytest.mark.parametrize("group,stripe", [(1, 0), (8, 256)])
def test_single_sort_matches_twosort_reference(group, stripe):
    """ISSUE 2 restage gate: the single-sort pipeline must match the
    original two-sort pipeline's output EXACTLY — perm, slot planes,
    row_block, per-stripe row bounds, weights, degrees, edge count —
    on a duplicate-laden fixed graph. The one intentional restage
    divergence is the relabel key (raw vs unique in-degree, see the
    module docstring of ops/device_build.py); the fixture pins it by
    placing its duplicates on the already-top in-degree vertex and
    ASSERTING the two orderings agree, so everything downstream —
    dedup flags, degree correction, slot assignment with weight-0
    duplicate slots — must be bit-identical, not merely equivalent."""
    rng = np.random.default_rng(97)
    n, e = 600, 4000
    src0 = rng.integers(0, n, e)
    dst0 = rng.integers(0, n, e)
    # Duplicate-free base (random draws collide), then 80 controlled
    # duplicate copies of edges into the max-in-degree vertex — its
    # raw in-degree grows but it stays the max, so the raw and unique
    # relabel orderings stay identical (asserted below).
    key = np.unique(src0.astype(np.int64) * n + dst0)
    src = (key // n).astype(np.int32)
    dst = (key % n).astype(np.int32)
    hot = int(np.bincount(dst, minlength=n).argmax())
    hot_src = src[dst == hot][:8]
    src = np.concatenate([src, np.repeat(hot_src, 10)]).astype(np.int32)
    dst = np.concatenate(
        [dst, np.full(80, hot, np.int32)]
    ).astype(np.int32)

    ref = _twosort_reference(src, dst, n, group=group, stripe_size=stripe)
    raw_in = np.bincount(dst, minlength=n).astype(np.int64)
    assert np.array_equal(
        np.argsort(-raw_in, kind="stable"),
        np.argsort(-ref["in_degree"], kind="stable"),
    ), "fixture must not flip the relabel ordering (see docstring)"

    dg = db.build_ell_device(
        jax.numpy.asarray(src), jax.numpy.asarray(dst), n=n,
        weight_dtype=np.float64, group=group, stripe_size=stripe,
    )
    assert dg.num_edges == ref["num_edges"]
    np.testing.assert_array_equal(np.asarray(dg.perm), ref["perm"])
    np.testing.assert_array_equal(
        np.asarray(dg.out_degree), ref["out_degree"]
    )
    srcs = dg.src if isinstance(dg.src, list) else [dg.src]
    ws = dg.weight if isinstance(dg.weight, list) else [dg.weight]
    rbs = dg.row_block if isinstance(dg.row_block, list) else [dg.row_block]
    assert len(srcs) == len(ref["src"])
    for s in range(len(srcs)):
        np.testing.assert_array_equal(np.asarray(srcs[s]), ref["src"][s])
        np.testing.assert_array_equal(
            np.asarray(rbs[s]), ref["row_block"][s]
        )
        np.testing.assert_allclose(
            np.asarray(ws[s]), ref["weight"][s], rtol=0, atol=0
        )


def test_device_fingerprint_stable_and_discriminating():
    """fingerprint() must be identical for identical builds (incl.
    across the process-global x64 flip — the checksum dtype is pinned),
    and differ for a different graph."""
    rng = np.random.default_rng(5)
    n, e = 300, 2000
    # sources drawn below n-20: the top vertices are guaranteed sinks
    # (needed for the dangling-override case below)
    src, dst = rng.integers(0, n - 20, e), rng.integers(0, n, e)

    def build(s, d):
        return db.build_ell_device(
            jax.numpy.asarray(s), jax.numpy.asarray(d), n=n, group=4
        )

    fp1 = build(src, dst).fingerprint()
    jax.config.update("jax_enable_x64", True)  # one-way within a process
    fp2 = build(src, dst).fingerprint()
    assert fp1 == fp2 and fp1.startswith("dev-")
    assert build(dst, src).fingerprint() != fp1
    # Degree-PRESERVING rewire ({0->2, 1->3} vs {0->3, 1->2} shape):
    # identical degree vectors and perm, different adjacency — only a
    # slot-array checksum can tell these apart.
    a = db.build_ell_device(
        jax.numpy.asarray([0, 1]), jax.numpy.asarray([2, 3]), n=4
    ).fingerprint()
    b = db.build_ell_device(
        jax.numpy.asarray([0, 1]), jax.numpy.asarray([3, 2]), n=4
    ).fingerprint()
    assert a != b
    # The dangling-mask override is a semantic input in its own right
    # (crawl inputs: same edges, different crawled status) — snapshots
    # must not cross-validate between them, on EITHER build path. A
    # valid override is a SUBSET of the out-degree-0 vertices (a
    # crawled linkless page is not dangling), so build one that drops
    # half the default mask.
    from pagerank_tpu import build_graph

    hg1 = build_graph(src, dst, n=n)
    sinks = np.flatnonzero(hg1.out_degree == 0)
    assert len(sinks) >= 2, "test graph needs out-degree-0 vertices"
    mask = np.zeros(n, bool)
    mask[sinks[: len(sinks) // 2]] = True  # proper subset of the default
    fp_mask = db.build_ell_device(
        jax.numpy.asarray(src), jax.numpy.asarray(dst), n=n, group=4,
        dangling_mask=mask,
    ).fingerprint()
    assert fp_mask != fp1
    hg2 = build_graph(src, dst, n=n, dangling_mask=mask)
    assert hg1.fingerprint() != hg2.fingerprint()
