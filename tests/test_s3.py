"""The concrete S3-protocol backend (utils/s3) against an in-process
stub server — the reference's literal I/O form: 301 s3n:// SequenceFile
inputs and an S3 output bucket (Sparky.java:44-58,237). VERDICT r2 #5.
"""

import json
import os

import numpy as np
import pytest

from pagerank_tpu.cli import main
from pagerank_tpu.ingest import write_sequence_file
from pagerank_tpu.utils import fsio
from pagerank_tpu.utils.s3 import (
    S3_SCHEMES,
    S3FileSystem,
    register_s3,
    sign_v4,
)

from tests.s3stub import S3Stub


def test_sigv4_aws_reference_vector():
    """The signer must reproduce AWS's published SigV4 example
    (docs 'Signature Version 4 signing process', GET ListUsers on IAM,
    20150830T123600Z) bit-for-bit."""
    headers = {
        "content-type": "application/x-www-form-urlencoded; charset=utf-8",
        "host": "iam.amazonaws.com",
        "x-amz-date": "20150830T123600Z",
    }
    auth = sign_v4(
        "GET", "iam.amazonaws.com", "/",
        "Action=ListUsers&Version=2010-05-08", headers,
        "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855",
        region="us-east-1", service="iam",
        access_key="AKIDEXAMPLE",
        secret_key="wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        amzdate="20150830T123600Z",
    )
    assert auth == (
        "AWS4-HMAC-SHA256 "
        "Credential=AKIDEXAMPLE/20150830/us-east-1/iam/aws4_request, "
        "SignedHeaders=content-type;host;x-amz-date, "
        "Signature=5d672d79c15b13162d9279b0855cfba6"
        "789a8edb4c82c400e06b5924a6f2b5d7"
    )


@pytest.fixture
def s3fs():
    with S3Stub() as stub:
        fs = S3FileSystem(stub.endpoint)
        for scheme in S3_SCHEMES:
            fsio.register(scheme, fs)
        try:
            yield stub, fs
        finally:
            for scheme in S3_SCHEMES:
                fsio.unregister(scheme)


def test_s3_object_roundtrip(s3fs):
    stub, fs = s3fs
    with fsio.fopen("s3://b/dir/a.txt", "w") as f:
        f.write("hello")
    assert stub.objects["/b/dir/a.txt"] == b"hello"
    assert fsio.isfile("s3://b/dir/a.txt")
    assert fsio.isdir("s3://b/dir")
    assert fsio.exists("s3://b/dir/a.txt")
    assert not fsio.exists("s3://b/dir/missing")
    with fsio.fopen("s3://b/dir/a.txt") as f:
        assert f.read() == "hello"
    with fsio.fopen("s3://b/dir/a.txt", "a") as f:
        f.write(" world")
    with fsio.fopen("s3://b/dir/a.txt", "rb") as f:
        assert f.read() == b"hello world"
    with pytest.raises(FileNotFoundError):
        fsio.fopen("s3://b/missing", "rb")
    with pytest.raises(FileExistsError):
        fsio.fopen("s3://b/dir/a.txt", "x")
    # the same store answers any registered scheme spelling (the
    # reference writes s3n://, Sparky.java:44)
    with fsio.fopen("s3n://b/dir/a.txt") as f:
        assert f.read() == "hello world"
    # replace = server-side COPY + DELETE, atomic per object
    fsio.replace("s3://b/dir/a.txt", "s3://b/dir/b.txt")
    assert not fsio.isfile("s3://b/dir/a.txt")
    assert fsio.listdir("s3://b/dir") == ["b.txt"]
    with pytest.raises(FileNotFoundError):
        fsio.listdir("s3://b/nothing")
    # abort-on-exception: no partial object published
    with pytest.raises(RuntimeError):
        with fsio.fopen("s3://b/torn.bin", "wb") as f:
            f.write(b"partial")
            raise RuntimeError("die mid-write")
    assert not fsio.isfile("s3://b/torn.bin")


def test_s3_listdir_delimiter_and_pagination(s3fs):
    stub, fs = s3fs
    stub.max_page = 3  # force ListObjectsV2 continuation tokens
    for i in range(10):
        with fsio.fopen(f"s3://b/seg/metadata-{i:05d}", "wb") as f:
            f.write(b"x")
    with fsio.fopen("s3://b/seg/sub/deep.bin", "wb") as f:
        f.write(b"y")
    names = fsio.listdir("s3://b/seg")
    assert names == [f"metadata-{i:05d}" for i in range(10)] + ["sub"]
    assert fsio.listdir("s3://b") == ["seg"]


def test_s3_sigv4_header_sent_when_credentialed():
    with S3Stub() as stub:
        fs = S3FileSystem(stub.endpoint, access_key="AKIDTEST",
                          secret_key="secret")
        fsio.register("s3", fs)
        try:
            with fsio.fopen("s3://b/k", "wb") as f:
                f.write(b"data")
        finally:
            fsio.unregister("s3")
        auth = [a for a in stub.auth_headers if a]
        assert auth, "no Authorization header reached the server"
        assert auth[-1].startswith("AWS4-HMAC-SHA256 Credential=AKIDTEST/")
        assert "SignedHeaders=" in auth[-1] and "Signature=" in auth[-1]


def test_s3_env_autoregistration(monkeypatch):
    """With PAGERANK_TPU_S3_ENDPOINT set, s3:// paths work with no
    explicit registration (fsio.get_fs lazy hook)."""
    with S3Stub() as stub:
        monkeypatch.setenv("PAGERANK_TPU_S3_ENDPOINT", stub.endpoint)
        try:
            with fsio.fopen("s3://auto/k.txt", "w") as f:
                f.write("auto")
            with fsio.fopen("s3a://auto/k.txt") as f:
                assert f.read() == "auto"
        finally:
            for scheme in S3_SCHEMES:
                fsio.unregister(scheme)


def test_s3_multipart_upload(s3fs):
    """Objects over MULTIPART_PART_SIZE commit via the multipart
    protocol (initiate / part PUTs / complete) and read back intact —
    the path a >5 GB snapshot needs on real S3, where single PUT caps
    out (utils/s3.S3FileSystem._commit_multipart)."""
    stub, fs = s3fs
    fs.MULTIPART_PART_SIZE = 1024  # instance override: force the path
    data = bytes(range(256)) * 17  # 4352 B -> 5 parts, last one short
    with fsio.fopen("s3://b/big.bin", "wb") as f:
        f.write(data)
    assert stub.completed_multiparts == ["/b/big.bin"]
    assert stub.objects["/b/big.bin"] == data
    assert not stub.uploads  # no orphan upload state left behind
    with fsio.fopen("s3://b/big.bin", "rb") as f:
        assert f.read() == data
    # boundary: exactly one part size still takes the single-PUT path
    with fsio.fopen("s3://b/small.bin", "wb") as f:
        f.write(b"z" * 1024)
    assert stub.completed_multiparts == ["/b/big.bin"]
    assert stub.objects["/b/small.bin"] == b"z" * 1024
    # rename of a large object: real S3 caps single CopyObject at 5 GB,
    # so replace() must range-copy server-side (UploadPartCopy) — the
    # snapshot tmp+rename path for big rank vectors.
    fsio.replace("s3://b/big.bin", "s3://b/moved.bin")
    assert stub.objects["/b/moved.bin"] == data
    assert "/b/big.bin" not in stub.objects
    assert stub.completed_multiparts[-1] == "/b/moved.bin"
    assert not stub.uploads


def test_s3_streaming_ranged_reads(s3fs):
    """Objects over STREAM_THRESHOLD read through a seekable ranged
    reader: whole-file read() is ONE ranged GET, seek+partial reads
    fetch only the touched regions — so np.load on a big .npz snapshot
    pulls members, not the object (utils/s3._RangedReader)."""
    import numpy as np

    stub, fs = s3fs
    fs.STREAM_THRESHOLD = 1024
    data = bytes(range(256)) * 40  # 10240 B
    with fsio.fopen("s3://b/big.bin", "wb") as f:
        f.write(data)
    stub.auth_headers.clear()
    stub.range_requests.clear()
    with fsio.fopen("s3://b/big.bin", "rb") as f:
        assert f.read() == data
    # probe GET (first 1 KB) + ONE tail GET, no HEAD round-trip
    assert len(stub.range_requests) == 2
    assert len(stub.auth_headers) == 2
    # seek + partial read fetches only the touched regions
    stub.range_requests.clear()
    with fsio.fopen("s3://b/big.bin", "rb") as f:
        f.seek(5000)
        assert f.read(16) == data[5000:5016]
        f.seek(-8, 2)
        assert f.read() == data[-8:]
        f.seek(100)  # BufferedReader readahead extends past the head,
        assert f.read(8) == data[100:108]  # so this fetches the tail
    assert len(stub.range_requests) == 4  # probe + three region fetches
    # small objects arrive whole in the single probe request
    with fsio.fopen("s3://b/small.bin", "wb") as f:
        f.write(b"tiny")
    stub.auth_headers.clear()
    stub.range_requests.clear()
    with fsio.fopen("s3://b/small.bin", "rb") as f:
        assert f.read() == b"tiny"
    assert len(stub.auth_headers) == 1  # exactly one request total
    # zero-byte objects (the '_SUCCESS' markers): real S3 answers the
    # probe with 416 InvalidRange — must resolve to an empty stream
    with fsio.fopen("s3://b/empty", "wb") as f:
        pass
    with fsio.fopen("s3://b/empty", "rb") as f:
        assert f.read() == b""
    # a zip-backed consumer (np.load mirrors the snapshot format) only
    # touches the central directory + the member it asks for
    buf = fsio.fopen("s3://b/arr.npz", "wb")
    np.savez(buf, a=np.arange(4000), b=np.zeros(4000))
    buf.close()
    stub.range_requests.clear()
    with fsio.fopen("s3://b/arr.npz", "rb") as f:
        loaded = np.load(f)
        np.testing.assert_array_equal(loaded["a"], np.arange(4000))
    assert stub.range_requests, "np.load did not stream"


def test_s3_multipart_failure_aborts(s3fs):
    """A failed part PUT aborts the multipart upload (no orphan parts
    accruing storage server-side) and surfaces the error."""
    stub, fs = s3fs
    fs.MULTIPART_PART_SIZE = 1024
    stub.fail_part = 3
    with pytest.raises(OSError):
        with fsio.fopen("s3://b/doomed.bin", "wb") as f:
            f.write(b"q" * 5000)
    assert not stub.uploads  # aborted, not leaked
    assert "/b/doomed.bin" not in stub.objects


def _meta(targets):
    return json.dumps(
        {"content": {"links": [{"type": "a", "href": t} for t in targets]}}
    )


def test_cli_seqfile_segment_and_snapshots_through_s3(s3fs, tmp_path):
    """End-to-end at the CLI surface, the reference's exact I/O shape:
    read a multi-file SequenceFile segment from an s3n:// directory URI,
    write snapshots and final ranks back to the store (Sparky.java
    reads s3n:// segments :44-61 and saves to S3 :237)."""
    stub, fs = s3fs
    records = [
        ("http://a/", _meta(["http://b/", "http://c/"])),
        ("http://b/", _meta(["http://a/"])),
        ("http://c/", _meta([])),
    ]
    # one record per segment file, like the reference's metadata-000NN
    for i, rec in enumerate(records):
        write_sequence_file(f"s3n://crawl/seg/metadata-{i:05d}", [rec])
    assert len(fsio.listdir("s3n://crawl/seg")) == 3

    rc = main([
        "--input", "s3n://crawl/seg", "--iters", "4", "--engine", "cpu",
        "--snapshot-dir", "s3://out/ck", "--dump-text-dir", "s3://out/txt",
        "--out", "s3://out/ranks.tsv", "--log-every", "0",
    ])
    assert rc == 0
    # ranks for every url, readable back through the store
    with fsio.fopen("s3://out/ranks.tsv") as f:
        ranks = dict(l.split("\t") for l in f.read().splitlines())
    assert set(ranks) == {"http://a/", "http://b/", "http://c/"}
    # snapshots + reference-style per-iteration text dumps landed
    assert fsio.listdir("s3://out/ck") == [
        f"ranks_iter{i}.npz" for i in range(1, 5)
    ]
    assert fsio.listdir("s3://out/txt/PageRank0") == ["_SUCCESS", "part-00000"]
    # resume from the s3 snapshot and run further
    rc = main([
        "--input", "s3n://crawl/seg", "--iters", "6", "--engine", "cpu",
        "--snapshot-dir", "s3://out/ck", "--resume", "--log-every", "0",
    ])
    assert rc == 0
    assert "ranks_iter6.npz" in fsio.listdir("s3://out/ck")
