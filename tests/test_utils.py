"""Synth generator + metrics tests."""

import numpy as np

from pagerank_tpu.utils.metrics import MetricsLogger
from pagerank_tpu.utils.synth import rmat_edges, uniform_edges


def test_rmat_shapes_and_range():
    src, dst = rmat_edges(10, edge_factor=8, seed=1)
    assert src.shape == dst.shape == (8 << 10,)
    assert src.min() >= 0 and src.max() < 1 << 10
    assert dst.min() >= 0 and dst.max() < 1 << 10


def test_rmat_is_deterministic_and_skewed():
    s1, d1 = rmat_edges(12, seed=7)
    s2, d2 = rmat_edges(12, seed=7)
    np.testing.assert_array_equal(s1, s2)
    np.testing.assert_array_equal(d1, d2)
    # Power-law-ish: max out-degree far above the mean (16).
    deg = np.bincount(s1, minlength=1 << 12)
    assert deg.max() > 10 * deg.mean()


def test_uniform_edges():
    src, dst = uniform_edges(100, 1000, seed=0)
    assert src.shape == (1000,)
    deg = np.bincount(src, minlength=100)
    assert deg.max() < 5 * deg.mean()  # no heavy tail


def test_metrics_logger_summary(tmp_path):
    jsonl = str(tmp_path / "m.jsonl")
    m = MetricsLogger(num_edges=1000, num_chips=2, log_every=0, jsonl_path=jsonl)
    for i in range(3):
        m(i, {"l1_delta": 0.5 / (i + 1), "dangling_mass": 1.0})
    s = m.summary()
    m.close()
    assert s["iters"] == 3
    assert s["timed_iters"] == 2  # compile iteration 0 excluded from means
    assert s["edges_per_sec_per_chip"] > 0
    assert len(open(jsonl).readlines()) == 3
    # The explicit-args (fused) form: every executed iteration is timed.
    m2 = MetricsLogger(num_edges=1000, num_chips=2, log_every=0)
    s2 = m2.summary(iters=5, total_seconds=2.0)
    assert s2["iters"] == s2["timed_iters"] == 5


def test_lane_group_auto_resolution():
    from pagerank_tpu.utils.config import PageRankConfig

    cfg = PageRankConfig().validate()  # default 0 = auto
    assert cfg.effective_lane_group(pair=False) == 64
    assert cfg.effective_lane_group(pair=True) == 16
    # r3 re-measurement: striped pair ALSO prefers 16 (the r2 flip to
    # 64 inverted under the current multi-dispatch + chunk autotune)
    assert cfg.effective_lane_group(pair=True, striped=True) == 16
    assert cfg.effective_lane_group(pair=False, striped=True) == 64
    # occupancy-WIDENED pair spans drop to 8
    assert cfg.effective_lane_group(pair=True, striped=True, widened=True) == 8
    assert cfg.effective_lane_group(pair=False, striped=True, widened=True) == 64
    # explicit values pass through untouched
    assert PageRankConfig(lane_group=8).validate().effective_lane_group(
        pair=True
    ) == 8
    import pytest

    with pytest.raises(ValueError):
        PageRankConfig(lane_group=3).validate()


def test_tol_validation():
    import math

    import pytest

    from pagerank_tpu.utils.config import PageRankConfig

    PageRankConfig(tol=1e-6).validate()
    for bad in (0.0, -1.0, float("inf"), math.nan):
        with pytest.raises(ValueError, match="tol"):
            PageRankConfig(tol=bad).validate()


def test_oracle_l1_known_vectors():
    """oracle_l1 is the single source of the acceptance gate metrics:
    pin it on hand-computed vectors (incl. the global-scale-offset case
    the mass normalization exists for)."""
    import pytest

    from pagerank_tpu.utils.metrics import oracle_l1

    r_ref = np.array([1.0, 2.0, 5.0])
    # Pure global scale offset: raw L1 sees it, mass-normalized is 0.
    l1, norm, mass = oracle_l1(r_ref * 1.01, r_ref)
    assert l1 == pytest.approx(0.08)
    assert norm == pytest.approx(0.01)
    assert mass == pytest.approx(0.0, abs=1e-15)
    # Pure redistribution at constant mass: both see it.
    l1, norm, mass = oracle_l1(np.array([2.0, 1.0, 5.0]), r_ref)
    assert l1 == pytest.approx(2.0)
    assert norm == pytest.approx(0.25)
    assert mass == pytest.approx(0.25)
    # Identity.
    assert oracle_l1(r_ref, r_ref) == (0.0, 0.0, 0.0)


def test_tuning_cache_roundtrip(tmp_path, monkeypatch):
    # Build-time tuning decisions (e.g. the ELL chunk autotune winner)
    # persist next to the compile cache and survive junk in the file.
    from pagerank_tpu.utils import compile_cache as cc

    monkeypatch.setattr(cc, "_active_cache_dir", lambda: str(tmp_path))
    assert cc.tuning_get("chunk:x") is None
    cc.tuning_put("chunk:x", 2048)
    cc.tuning_put("chunk:y", 256)
    assert cc.tuning_get("chunk:x") == 2048
    assert cc.tuning_get("chunk:y") == 256
    # corrupt file: reads degrade to None, writes recover
    (tmp_path / "tuning.json").write_text("{broken")
    assert cc.tuning_get("chunk:x") is None
    cc.tuning_put("chunk:x", 512)
    assert cc.tuning_get("chunk:x") == 512


def test_stage_call_executable_cache_ignores_x64_flip():
    """The build-stage executable cache (ISSUE 2): a stage compiles
    once per (name, avals, statics) — NOT per x64 state, which is the
    point of pinning the build chain to 32-bit (PTC006) — and reports
    compile seconds only on the miss."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import disable_x64

    from pagerank_tpu.utils import compile_cache as cc

    def inc(x):
        return x + jnp.int32(1)

    a = jnp.arange(8, dtype=jnp.int32)
    cc.clear_stage_cache()
    t1 = {}
    r1 = cc.stage_call("t_inc", inc, (a,), timings=t1)
    assert t1.get("compile_s", 0.0) > 0.0  # miss: compile attributed
    t2 = {}
    r2 = cc.stage_call("t_inc", inc, (a,), timings=t2)
    assert "compile_s" not in t2  # in-process hit
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
    # The key deliberately ignores the process-global x64 flag (the
    # conftest runs with it ON): flipping it must still hit.
    with disable_x64():
        t3 = {}
        r3 = cc.stage_call("t_inc", inc, (a,), timings=t3)
    assert "compile_s" not in t3
    np.testing.assert_array_equal(np.asarray(r3), np.asarray(r1))
    # Different avals are a different executable.
    t4 = {}
    cc.stage_call("t_inc", inc, (jnp.arange(4, dtype=jnp.int32),),
                  timings=t4)
    assert t4.get("compile_s", 0.0) > 0.0
