"""Hadoop SequenceFile ingestion (the reference's literal input format,
Sparky.java:44-61): encoding primitives, roundtrip, parity with the TSV
crawl path, and CLI autodetection."""

import io
import json
import struct
import zlib

import numpy as np
import pytest

from pagerank_tpu.ingest import (
    load_crawl_file,
    load_crawl_seqfile,
    read_sequence_file,
    write_sequence_file,
)
from pagerank_tpu.ingest.seqfile import (
    TEXT_CLASS,
    _read_vint,
    _text_bytes,
    _write_vint,
    expand_seqfile_paths,
)


def meta(url, targets):
    links = [{"type": "a", "href": t} for t in targets]
    return json.dumps({"url": url, "content": {"links": links}})


RECORDS = [
    ("http://a.example/", meta("http://a.example/", ["http://b.example/",
                                                     "http://c.example/"])),
    ("http://b.example/", meta("http://b.example/", ["http://a.example/"])),
    ("http://c.example/", meta("http://c.example/", [])),  # linkless page
    ("http://d.example/", meta("http://d.example/", ["http://x.example/"])),
]


def test_vint_roundtrip_hadoop_values():
    # Hadoop WritableUtils boundary cases, incl. the single-byte range
    # [-112, 127] and multi-byte positives/negatives.
    for v in (0, 1, -1, 127, -112, 128, -113, 255, 256, 65535, -65536,
              2**31 - 1, -(2**31), 2**53):
        buf = io.BytesIO()
        _write_vint(buf, v)
        buf.seek(0)
        assert _read_vint(buf) == v, v


def test_vint_known_hadoop_encodings():
    # Values Hadoop encodes in one byte are stored verbatim.
    for v in (0, 5, 127, -100):
        buf = io.BytesIO()
        _write_vint(buf, v)
        assert buf.getvalue() == struct.pack("b", v)
    # 200 > 127: marker byte -113 (one payload byte), then 0xC8.
    buf = io.BytesIO()
    _write_vint(buf, 200)
    assert buf.getvalue() == bytes([0x8F, 0xC8])


def test_roundtrip(tmp_path):
    p = str(tmp_path / "part-00000")
    n = write_sequence_file(p, RECORDS, sync_every=2)  # exercise sync escapes
    assert n == len(RECORDS)
    assert list(read_sequence_file(p)) == RECORDS


def test_graph_matches_tsv_crawl_path(tmp_path):
    seq = str(tmp_path / "metadata-00000")
    write_sequence_file(seq, RECORDS)
    tsv = tmp_path / "crawl.tsv"
    tsv.write_text("".join(f"{u}\t{m}\n" for u, m in RECORDS))

    g1, ids1 = load_crawl_seqfile(seq)
    g2, ids2 = load_crawl_file(str(tsv))
    assert g1.n == g2.n and g1.num_edges == g2.num_edges
    assert ids1.names == ids2.names
    np.testing.assert_array_equal(g1.src, g2.src)
    np.testing.assert_array_equal(g1.dst, g2.dst)
    np.testing.assert_array_equal(g1.dangling_mask, g2.dangling_mask)


def test_segment_directory_and_comma_list(tmp_path):
    d = tmp_path / "segment"
    d.mkdir()
    for i, rec in enumerate(RECORDS):
        write_sequence_file(str(d / f"metadata-{i:05d}"), [rec])
    (d / "_SUCCESS").write_text("")  # Hadoop job marker: must be skipped
    paths = expand_seqfile_paths(str(d))
    assert len(paths) == len(RECORDS)

    g_dir, _ = load_crawl_seqfile(str(d))
    g_one, _ = load_crawl_seqfile(
        ",".join(str(d / f"metadata-{i:05d}") for i in range(len(RECORDS)))
    )
    assert g_dir.num_edges == g_one.num_edges
    assert g_dir.n == g_one.n


def test_record_compressed_deflate(tmp_path):
    # Hand-build a record-compressed (DefaultCodec) file; values are
    # deflate(serialized Text).
    p = tmp_path / "deflate.seq"
    sync = bytes(range(16))
    with open(p, "wb") as f:
        f.write(b"SEQ" + bytes([6]))
        f.write(_text_bytes(TEXT_CLASS))
        f.write(_text_bytes(TEXT_CLASS))
        f.write(b"\x01\x00")
        f.write(_text_bytes("org.apache.hadoop.io.compress.DefaultCodec"))
        f.write(struct.pack(">i", 0))
        f.write(sync)
        k = _text_bytes("http://a/")
        v = zlib.compress(_text_bytes(meta("http://a/", ["http://b/"])))
        f.write(struct.pack(">i", len(k) + len(v)))
        f.write(struct.pack(">i", len(k)))
        f.write(k)
        f.write(v)
    pairs = list(read_sequence_file(str(p)))
    assert pairs[0][0] == "http://a/"
    assert "http://b/" in pairs[0][1]


@pytest.mark.parametrize("compression", ["record", "block"])
def test_compressed_roundtrip(tmp_path, compression):
    p = str(tmp_path / f"{compression}.seq")
    n = write_sequence_file(p, RECORDS, compression=compression)
    assert n == len(RECORDS)
    assert list(read_sequence_file(p)) == RECORDS
    # Compressed modes must be smaller than raw on redundant data.
    raw = str(tmp_path / "raw.seq")
    big = [(f"http://u{i}/", meta(f"http://u{i}/", ["http://t/"] * 20))
           for i in range(200)]
    write_sequence_file(raw, big)
    write_sequence_file(p, big, compression=compression)
    import os

    assert os.path.getsize(p) < os.path.getsize(raw)


def test_block_compressed_multiple_blocks(tmp_path):
    # A tiny block_size forces many blocks; every record must survive,
    # in order, across block boundaries.
    p = str(tmp_path / "blocks.seq")
    recs = [(f"http://u{i:04d}/", meta(f"http://u{i:04d}/",
                                       [f"http://t{i % 7}/"]))
            for i in range(500)]
    write_sequence_file(p, recs, compression="block", block_size=2048)
    assert list(read_sequence_file(p)) == recs
    # More than one block actually got written (each starts with the
    # sync escape); count escapes in the body.
    blob = open(p, "rb").read()
    assert blob.count(struct.pack(">i", -1)) > 3


def test_block_compressed_graph_matches_uncompressed(tmp_path):
    plain = str(tmp_path / "plain.seq")
    block = str(tmp_path / "block.seq")
    write_sequence_file(plain, RECORDS)
    write_sequence_file(block, RECORDS, compression="block")
    g1, ids1 = load_crawl_seqfile(plain)
    g2, ids2 = load_crawl_seqfile(block)
    assert ids1.names == ids2.names
    np.testing.assert_array_equal(g1.src, g2.src)
    np.testing.assert_array_equal(g1.dst, g2.dst)


def test_block_compressed_corrupt_sync_rejected(tmp_path):
    p = str(tmp_path / "bad.seq")
    write_sequence_file(p, RECORDS, compression="block")
    blob = bytearray(open(p, "rb").read())
    # Flip a byte inside the block's sync marker (header is
    # magic+2 classnames+flags+codec+metadata+sync; the block sync
    # starts right after the -1 escape — find the first escape).
    i = blob.index(struct.pack(">i", -1)) + 4
    blob[i] ^= 0xFF
    open(p, "wb").write(bytes(blob))
    with pytest.raises(ValueError, match="sync marker mismatch"):
        list(read_sequence_file(p))


def test_unknown_compression_mode_rejected(tmp_path):
    with pytest.raises(ValueError, match="unknown compression"):
        write_sequence_file(str(tmp_path / "x.seq"), RECORDS,
                            compression="snappy")


@pytest.mark.parametrize(
    "mutate, err",
    [
        (lambda b: b"BAD" + b[3:], "not a SequenceFile"),
        (lambda b: b[:3] + bytes([4]) + b[4:], "version"),
        (lambda b: b[:-10], "truncated|EOF"),
    ],
)
def test_malformed_files_rejected(tmp_path, mutate, err):
    import re

    p = str(tmp_path / "x.seq")
    write_sequence_file(p, RECORDS)
    blob = open(p, "rb").read()
    open(p, "wb").write(mutate(blob))
    with pytest.raises((ValueError, EOFError)) as ei:
        list(read_sequence_file(p))
    assert re.search(err, str(ei.value), re.I)


def test_non_text_classes_rejected(tmp_path):
    p = str(tmp_path / "x.seq")
    with open(p, "wb") as f:
        f.write(b"SEQ" + bytes([6]))
        f.write(_text_bytes("org.apache.hadoop.io.LongWritable"))
        f.write(_text_bytes(TEXT_CLASS))
        f.write(b"\x00\x00")
        f.write(struct.pack(">i", 0))
        f.write(bytes(16))
    with pytest.raises(ValueError, match="Text/Text"):
        list(read_sequence_file(p))


def test_cli_seqfile_autodetect(tmp_path):
    from pagerank_tpu.cli import main

    d = tmp_path / "seg"
    d.mkdir()
    write_sequence_file(str(d / "metadata-00000"), RECORDS)
    out = tmp_path / "r.tsv"
    rc = main(["--input", str(d), "--iters", "5", "--out", str(out),
               "--log-every", "0"])
    assert rc == 0
    ranks = {l.split("\t")[0]: float(l.split("\t")[1]) for l in open(out)}
    # Vertex universe: 4 crawled + 1 uncrawled target (x.example).
    assert len(ranks) == 5 and "http://x.example/" in ranks

    # equivalent run through the TSV path gives identical ranks
    tsv = tmp_path / "c.tsv"
    tsv.write_text("".join(f"{u}\t{m}\n" for u, m in RECORDS))
    out2 = tmp_path / "r2.tsv"
    assert main(["--input", str(tsv), "--iters", "5", "--out", str(out2),
                 "--log-every", "0"]) == 0
    ranks2 = {l.split("\t")[0]: float(l.split("\t")[1]) for l in open(out2)}
    assert ranks == ranks2


def test_cli_comma_in_filename_still_plain_file(tmp_path):
    from pagerank_tpu.cli import main

    p = tmp_path / "a,b.txt"
    p.write_text("0 1\n1 0\n")
    out = tmp_path / "r.tsv"
    assert main(["--input", str(p), "--iters", "2", "--out", str(out),
                 "--log-every", "0"]) == 0


def test_truncated_length_field_raises_eoferror(tmp_path):
    p = str(tmp_path / "x.seq")
    write_sequence_file(p, RECORDS)
    blob = open(p, "rb").read()
    # chop mid key-length of the first record: header end = start of
    # first record; cut 2 bytes into its key-length field
    # (find the first record by re-reading offsets is overkill — just
    # binary-search a cut that lands inside a 4-byte field)
    for cut in range(len(blob) - 7, 60, -1):
        open(p, "wb").write(blob[:cut])
        try:
            list(read_sequence_file(p))
        except (EOFError, ValueError):
            continue  # every truncation must raise a documented type
        except Exception as e:  # pragma: no cover
            raise AssertionError(f"cut={cut}: undocumented {type(e).__name__}: {e}")


def test_segment_dir_skips_subdirectories(tmp_path):
    d = tmp_path / "seg"
    (d / "nested").mkdir(parents=True)
    write_sequence_file(str(d / "metadata-00000"), RECORDS)
    assert expand_seqfile_paths(str(d)) == [str(d / "metadata-00000")]


def test_golden_seqfile_to_text_dumps_vs_rdd_oracle(tmp_path):
    """The full reference workflow, end to end: a crawl segment in the
    reference's literal on-disk format (SequenceFiles of url/json) runs
    through the CLI with per-iteration text dumps, and EVERY iterate is
    diffed against the dict-based RDD transliteration of Sparky.java —
    the SURVEY §4 golden pipeline ("per-iteration snapshots mirror
    Sparky.java:237 and are diffed iterate-by-iterate")."""
    import re

    from pagerank_tpu.cli import main
    from tests.oracle_rdd import sparky_pagerank

    rng = np.random.default_rng(17)
    urls = [f"http://p{i}.example/" for i in range(40)]
    plain_records = []
    for i, u in enumerate(urls):
        k = int(rng.integers(0, 5))
        targets = sorted({urls[j] for j in rng.integers(0, 40, k)})
        if i in (7, 13):
            targets = []  # crawled, linkless (dangling sentinel path)
        if i == 21:
            targets = ["http://uncrawled.example/"]  # uncrawled target
        plain_records.append((u, targets))

    seg = tmp_path / "segment"
    seg.mkdir()
    seq_records = [(u, meta(u, ts)) for u, ts in plain_records]
    write_sequence_file(str(seg / "metadata-00000"), seq_records[:20])
    write_sequence_file(str(seg / "metadata-00001"), seq_records[20:])

    dumps = tmp_path / "dumps"
    rc = main(["--input", str(seg), "--iters", "10",
               "--dump-text-dir", str(dumps), "--dtype", "float64",
               "--accum-dtype", "float64", "--log-every", "0"])
    assert rc == 0

    _, history, _, _ = sparky_pagerank(plain_records, num_iters=10)
    line = re.compile(r"^\((.+),([-0-9.e+]+)\)$")
    for it in range(10):
        part = dumps / f"PageRank{it}" / "part-00000"
        got = {}
        for l in open(part):
            m = line.match(l.strip())
            assert m, l
            got[m.group(1)] = float(m.group(2))
        want = history[it]
        assert got.keys() == want.keys(), it
        for u in want:
            assert abs(got[u] - want[u]) < 1e-9, (it, u, got[u], want[u])


def test_parallel_segment_parse_identical_to_serial(tmp_path):
    """A 300-file segment (the reference's input shape: metadata-00000..
    00300, Sparky.java:44-58) parsed with a process pool must produce
    byte-identical graph structure AND id assignment to the serial path
    (record order is the id order). VERDICT r2 #2."""
    d = tmp_path / "segment"
    d.mkdir()
    rng = np.random.default_rng(0)
    n_files, n_urls = 300, 120
    urls = [f"http://site{i}.example/" for i in range(n_urls)]
    for i in range(n_files):
        recs = []
        for _ in range(3):
            u = urls[int(rng.integers(n_urls))]
            targets = [urls[int(t)] for t in
                       rng.integers(0, n_urls, int(rng.integers(0, 4)))]
            recs.append((u, meta(u, targets)))
        write_sequence_file(str(d / f"metadata-{i:05d}"), recs)

    g_ser, ids_ser = load_crawl_seqfile(str(d), workers=1)
    g_par, ids_par = load_crawl_seqfile(str(d), workers=4)
    assert ids_par.names == ids_ser.names  # identical id assignment
    assert g_par.fingerprint() == g_ser.fingerprint()
    np.testing.assert_array_equal(g_par.dangling_mask, g_ser.dangling_mask)


def test_parallel_segment_parse_propagates_strict_errors(tmp_path):
    d = tmp_path / "segment"
    d.mkdir()
    for i in range(4):
        write_sequence_file(str(d / f"metadata-{i:05d}"), [RECORDS[0]])
    write_sequence_file(str(d / "metadata-00004"),
                        [("http://bad.example/", "{not json")])
    with pytest.raises(Exception):
        load_crawl_seqfile(str(d), strict=True, workers=4)
    g, _ = load_crawl_seqfile(str(d), strict=False, workers=4)
    assert g.n > 0


def test_truncated_magic_raises_valueerror(tmp_path):
    # A file of exactly b"SEQ" (3 bytes) must raise the same FORMAT
    # ValueError as the native reader, not IndexError on magic[3]
    # (ADVICE r3).
    for blob in (b"", b"S", b"SE", b"SEQ"):
        p = str(tmp_path / "trunc.seq")
        open(p, "wb").write(blob)
        with pytest.raises(ValueError, match="not a SequenceFile"):
            list(read_sequence_file(p))
