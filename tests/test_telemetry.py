"""Device & convergence telemetry tests (ISSUE 5; docs/OBSERVABILITY.md
"Live monitoring" / "Cost model"): Prometheus text-format golden +
syntax tests, HTTP endpoint round-trip, virtual-time stall-watchdog
fire/no-fire, probe parity against the CPU oracle, the zero-probe-call
booby trap, histogram quantiles, and the XLA cost-accounting ledger."""

import json
import re
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pagerank_tpu import PageRankConfig, build_graph, make_engine, obs
from pagerank_tpu.engines.jax_engine import JaxTpuEngine
from pagerank_tpu.obs import costs as obs_costs
from pagerank_tpu.obs import live as obs_live
from pagerank_tpu.obs.metrics import Histogram, MetricsRegistry


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Process-global registry/ledger/watchdog must never leak between
    tests (the obs-test discipline, tests/test_obs.py)."""
    obs.disable_tracing()
    obs.get_registry().reset()
    obs_costs.reset()
    obs.disarm_watchdog()
    yield
    obs.disable_tracing()
    obs.get_registry().reset()
    obs_costs.reset()
    obs.disarm_watchdog()


def _graph(n=600, e=4800, seed=0):
    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


# -- Prometheus text format -------------------------------------------------


def test_prometheus_render_golden():
    """Exact rendering of one counter + gauge + histogram — the
    name/help/type-line/bucket syntax a scraper parses."""
    reg = MetricsRegistry()
    reg.counter("s3.request.retries", "transparent re-attempts").inc(5)
    reg.gauge("solve.iteration", "iterations completed").set(7)
    h = reg.histogram("snapshot.save_bytes", "per-snapshot size")
    for v in (3, 5, 1000):
        h.record(v)
    assert obs_live.render_prometheus(reg) == (
        "# HELP pagerank_s3_request_retries transparent re-attempts\n"
        "# TYPE pagerank_s3_request_retries counter\n"
        "pagerank_s3_request_retries 5\n"
        "# HELP pagerank_snapshot_save_bytes per-snapshot size\n"
        "# TYPE pagerank_snapshot_save_bytes histogram\n"
        'pagerank_snapshot_save_bytes_bucket{le="4"} 1\n'
        'pagerank_snapshot_save_bytes_bucket{le="8"} 2\n'
        'pagerank_snapshot_save_bytes_bucket{le="1024"} 3\n'
        'pagerank_snapshot_save_bytes_bucket{le="+Inf"} 3\n'
        "pagerank_snapshot_save_bytes_sum 1008.0\n"
        "pagerank_snapshot_save_bytes_count 3\n"
        "# HELP pagerank_solve_iteration iterations completed\n"
        "# TYPE pagerank_solve_iteration gauge\n"
        "pagerank_solve_iteration 7\n"
    )


_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"          # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r" (?:[-+]?(?:\d+\.?\d*(?:[eE][-+]?\d+)?|Inf)|NaN)$"
)


def assert_prometheus_syntax(text: str) -> int:
    """Strict line-by-line parse of an exposition-format document;
    returns the sample count. Shared with the acceptance smoke H."""
    samples = 0
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            assert re.match(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$", parts[2]), line
            if line.startswith("# TYPE "):
                assert parts[3] in ("counter", "gauge", "histogram",
                                    "summary", "untyped"), line
            continue
        assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"
        samples += 1
    return samples


def test_prometheus_syntax_over_live_registry():
    """Every metric the package actually registers must render to
    spec-parseable lines (gauges with None values publish nothing)."""
    reg = obs.get_registry()
    reg.counter("a.b", "c").inc()
    reg.gauge("unset.gauge", "never set")  # no sample line
    reg.gauge("neg.gauge", "negative").set(-2.5)
    h = reg.histogram("h.zero", "zero bucket")
    h.record(0)
    h.record(2 ** 70)  # lands in the +inf bucket
    text = obs_live.render_prometheus(reg)
    assert assert_prometheus_syntax(text) >= 3
    assert not any(
        l.startswith("pagerank_unset_gauge ") for l in text.splitlines()
    )  # metadata only, no sample line
    assert 'pagerank_h_zero_bucket{le="0"} 1' in text
    assert 'pagerank_h_zero_bucket{le="+Inf"} 2' in text


def test_prometheus_nonfinite_values_use_format_spellings():
    """NaN/±Inf gauges (a diverging solve under --no-health-checks)
    must render as the exposition format's 'NaN'/'+Inf'/'-Inf', never
    Python's repr — the strict parser's grammar rejects 'nan'."""
    reg = MetricsRegistry()
    reg.gauge("bad.mass", "diverged").set(float("nan"))
    reg.gauge("pos.inf", "over").set(float("inf"))
    reg.gauge("neg.inf", "under").set(float("-inf"))
    text = obs_live.render_prometheus(reg)
    assert "pagerank_bad_mass NaN" in text
    assert "pagerank_pos_inf +Inf" in text
    assert "pagerank_neg_inf -Inf" in text
    assert assert_prometheus_syntax(text) == 3


def test_metrics_textfile_atomic_rewrite(tmp_path):
    """--metrics-textfile: every write is a complete document (tmp +
    rename), and repeated writes reflect the current registry."""
    path = str(tmp_path / "metrics.prom")
    reg = obs.get_registry()
    c = reg.counter("solve.iterations", "done")
    exp = obs.MetricsExporter(textfile=path)
    c.inc()
    exp.write_textfile()
    first = open(path).read()
    assert "pagerank_solve_iterations 1" in first
    c.inc(4)
    exp.write_textfile()
    assert "pagerank_solve_iterations 5" in open(path).read()
    assert not (tmp_path / "metrics.prom.prom.tmp").exists()
    exp.close()
    assert_prometheus_syntax(open(path).read())


def test_http_endpoint_roundtrip():
    """--metrics-port on an ephemeral port: GET /metrics returns the
    current rendering with the exposition content type; other paths
    404; close() tears the server down."""
    reg = obs.get_registry()
    reg.counter("probe.points", "probes").inc(3)
    with obs.MetricsExporter(port=0) as exp:
        assert exp.port and exp.port > 0
        url = f"http://127.0.0.1:{exp.port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        assert body == exp.render()
        assert "pagerank_probe_points 3" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/nope", timeout=10
            )
        port = exp.port
    with pytest.raises(OSError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=2
        )


# -- histogram quantiles (ISSUE 5 satellite) --------------------------------


def test_histogram_quantiles_from_buckets():
    h = Histogram("t", "")
    for v in range(1, 101):  # 1..100
        h.record(v)
    s = h.snapshot()
    assert set(s) >= {"p50", "p90", "p99", "count", "sum", "buckets"}
    # Bucket-upper-bound estimates: p50 of 1..100 lands in the 64
    # bucket, p90/p99 in the 128 bucket (clamped to max=100).
    assert s["p50"] == 64
    assert s["p90"] == 100  # 128 bucket, clamped to observed max
    assert s["p99"] == 100
    assert Histogram("e", "").snapshot()["p50"] is None


def test_histogram_quantile_single_value_is_exact():
    h = Histogram("t", "")
    h.record(7)
    s = h.snapshot()
    # One observation: every quantile is that value (clamping to the
    # observed range beats the bucket ceiling of 8).
    assert s["p50"] == s["p99"] == 7


# -- stall watchdog ---------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_watchdog_virtual_time_fire_and_no_fire():
    clock = _Clock()
    interrupts = []
    wd = obs_live.StallWatchdog(
        timeout_s=10.0, action="warn", clock=clock,
        interrupt=lambda: interrupts.append(1),
    )
    # Heartbeats inside the timeout: never fires.
    for _ in range(5):
        clock.t += 8.0
        wd.heartbeat(3)
        assert wd.check() is False
    assert wd.stalls == 0
    # Silence past the timeout: fires ONCE per episode.
    clock.t += 11.0
    assert wd.check() is True
    assert wd.check() is False  # same episode, one diagnostic
    assert wd.stalls == 1
    assert interrupts == []  # warn action never interrupts
    assert wd.last_iteration == 3
    # New progress re-arms; a second stall fires a second episode.
    wd.heartbeat(9)
    assert wd.check() is False
    clock.t += 20.0
    assert wd.check() is True
    assert wd.stalls == 2
    snap = obs.get_registry().snapshot()
    assert snap["counters"]["watchdog.stalls"] == 2


def test_watchdog_raise_action_interrupts():
    clock = _Clock()
    interrupts = []
    wd = obs_live.StallWatchdog(
        timeout_s=5.0, action="raise", clock=clock,
        interrupt=lambda: interrupts.append(1),
    )
    clock.t += 6.0
    assert wd.check() is True
    assert interrupts == [1]


def test_watchdog_heartbeat_fed_by_engine_run():
    """An armed watchdog sees every completed step of engine.run (the
    solve/step completion feed)."""
    clock = _Clock()
    wd = obs_live.StallWatchdog(timeout_s=1e9, clock=clock)
    obs_live._WATCHDOG = wd  # arm without starting the thread
    try:
        eng = make_engine("cpu", PageRankConfig(num_iters=4)).build(_graph())
        eng.run()
    finally:
        obs_live._WATCHDOG = None
    assert wd.last_iteration == 3  # last completed iteration index


def test_watchdog_validation():
    with pytest.raises(ValueError):
        obs_live.StallWatchdog(timeout_s=0)
    with pytest.raises(ValueError):
        obs_live.StallWatchdog(timeout_s=5, action="explode")


# -- convergence probes -----------------------------------------------------


def test_probe_parity_device_vs_cpu_oracle():
    """Acceptance: a probed run's per-K residual / rank mass / top-k
    churn (and the decoded top-k sets themselves) from the device
    engine match the CPU oracle to dtype tolerance."""
    g = _graph()
    probes_j = obs.ConvergenceProbes(2, topk=16)
    eng = make_engine("jax", PageRankConfig(
        num_iters=8, num_devices=2)).build(g)
    r_jax = eng.run(probes=probes_j)

    probes_c = obs.ConvergenceProbes(2, topk=16)
    cpu = make_engine("cpu", PageRankConfig(
        num_iters=8, dtype="float64", accum_dtype="float64")).build(g)
    r_cpu = cpu.run(probes=probes_c)

    assert len(probes_j.history) == len(probes_c.history) == 4
    for a, b in zip(probes_j.history, probes_c.history):
        assert a["iteration"] == b["iteration"]
        # f32 device vs f64 oracle: dtype tolerance.
        assert a["l1_residual"] == pytest.approx(b["l1_residual"],
                                                 rel=1e-4)
        assert a["rank_mass"] == pytest.approx(b["rank_mass"], rel=1e-5)
        assert a["topk_churn"] == b["topk_churn"]
    # The decoded (original-id-space) top-k SETS agree.
    assert set(map(int, probes_j.last_topk_ids)) == set(
        map(int, probes_c.last_topk_ids)
    )
    np.testing.assert_allclose(r_jax, r_cpu, rtol=1e-4, atol=1e-6)


def test_probed_run_is_bit_identical_to_unprobed():
    """Probing must not perturb the solve: same graph, same config,
    ranks bit-for-bit equal with and without probes."""
    g = _graph(seed=3)
    cfg = PageRankConfig(num_iters=6, num_devices=2)
    r_plain = make_engine("jax", cfg).build(g).run()
    eng = make_engine("jax", cfg).build(g)
    r_probed = eng.run(probes=obs.ConvergenceProbes(3, topk=8))
    np.testing.assert_array_equal(r_plain, r_probed)


def test_zero_probe_call_booby_trap(monkeypatch):
    """--probe-every 0 / probes=None takes the EXACT pre-probe code
    path: booby-trap every probe entry point and run a full solve —
    zero probe calls (the no-op tracer discipline, applied to
    probes)."""

    def boom(*a, **k):
        raise AssertionError("probe machinery touched on an unprobed run")

    from pagerank_tpu import engine as engine_mod

    monkeypatch.setattr(engine_mod.PageRankEngine, "step_probed", boom)
    monkeypatch.setattr(engine_mod.PageRankEngine, "probe_values", boom)
    monkeypatch.setattr(JaxTpuEngine, "step_probed", boom)
    monkeypatch.setattr(JaxTpuEngine, "probe_values", boom)
    monkeypatch.setattr(JaxTpuEngine, "_get_probe_fn", boom)
    monkeypatch.setattr(JaxTpuEngine, "_get_probed_step", boom)
    g = _graph(seed=5)
    eng = make_engine("jax", PageRankConfig(
        num_iters=3, num_devices=2)).build(g)
    r = eng.run()  # probes=None
    assert np.all(np.isfinite(r))
    cpu = make_engine("cpu", PageRankConfig(num_iters=3)).build(g)
    assert np.all(np.isfinite(cpu.run()))


def test_stop_tol_early_exit_at_probe_points_only():
    g = _graph(seed=7)
    probes = obs.ConvergenceProbes(5, topk=8, stop_tol=1e30)
    eng = make_engine("cpu", PageRankConfig(num_iters=50)).build(g)
    eng.run(probes=probes)
    # An absurdly loose tol stops at the FIRST probe point (iteration
    # 4 -> 5 iterations done), never earlier: the check is cadenced.
    assert eng.iteration == 5
    assert len(probes.history) == 1


def test_probe_config_validation():
    with pytest.raises(ValueError):
        obs.ConvergenceProbes(-1)
    with pytest.raises(ValueError):
        obs.ConvergenceProbes(2, topk=0)
    with pytest.raises(ValueError):
        obs.ConvergenceProbes(2, stop_tol=0.0)
    with pytest.raises(ValueError):
        PageRankConfig(stop_tol=1e-6).validate()  # needs probe_every
    PageRankConfig(stop_tol=1e-6, probe_every=4).validate()


def test_probe_gauges_and_history_records():
    g = _graph(seed=11)
    probes = obs.ConvergenceProbes(2, topk=8)
    eng = make_engine("cpu", PageRankConfig(num_iters=4)).build(g)
    infos = []
    eng.run(on_iteration=lambda i, info: infos.append(dict(info)),
            probes=probes)
    # Probe iterations carry the probe scalars in the on_iteration
    # info (the per-iteration history feed); others don't.
    assert "rank_mass" in infos[1] and "topk_churn" in infos[1]
    assert "rank_mass" not in infos[0]
    snap = obs.get_registry().snapshot()
    assert snap["counters"]["probe.points"] == 2
    assert snap["gauges"]["probe.rank_mass"] == pytest.approx(
        probes.history[-1]["rank_mass"]
    )


# -- cost accounting --------------------------------------------------------


def test_cost_harvest_from_compiled_program():
    compiled = jax.jit(lambda x: (x * 2.0).sum()).lower(
        jnp.ones((256, 256), jnp.float32)
    ).compile()
    rep = obs_costs.harvest("toy", compiled, num_edges=1000, iters=4)
    # The CPU backend reports both analyses (probed in-session); a
    # backend that doesn't yields None — the schema tolerates it, but
    # HERE we know the substrate reports.
    assert rep.flops and rep.flops > 0
    assert rep.bytes_accessed and rep.bytes_accessed > 0
    assert rep.peak_bytes and rep.peak_bytes > 0
    assert rep.bytes_per_iter == rep.bytes_accessed / 4
    assert rep.bytes_per_edge == pytest.approx(rep.bytes_accessed / 4 / 1000)
    snap = obs_costs.ledger_snapshot()
    assert set(snap) == {"toy"}
    assert snap["toy"]["flops"] == rep.flops
    # Mirrored into the registry as cost.* gauges.
    gauges = obs.get_registry().snapshot()["gauges"]
    assert gauges["cost.toy.flops"] == pytest.approx(rep.flops / 4)


def test_cost_roofline_attachment():
    compiled = jax.jit(lambda x: x + 1).lower(
        jnp.ones((1024,), jnp.float32)
    ).compile()
    rep = obs_costs.harvest("leg", compiled)
    rep.device_kind = "TPU v5e"  # pretend: CPU kinds are off-table
    out = obs_costs.attach_measurement("leg", 1e-3)
    assert out is rep and rep.seconds_per_iter == 1e-3
    assert rep.achieved_bytes_per_s == pytest.approx(
        rep.bytes_accessed / 1e-3
    )
    expected = rep.achieved_bytes_per_s / 819e9
    assert rep.roofline_fraction == pytest.approx(expected)
    assert obs_costs.attach_measurement("never-harvested", 1.0) is None


def test_hbm_peak_lookup():
    assert obs_costs.hbm_peak_bytes_per_s("TPU v5e") == 819e9
    assert obs_costs.hbm_peak_bytes_per_s("TPU v5 lite") == 819e9
    assert obs_costs.hbm_peak_bytes_per_s("TPU v5p") == 2_765e9
    assert obs_costs.hbm_peak_bytes_per_s("cpu") is None
    assert obs_costs.hbm_peak_bytes_per_s(None) is None


def test_engine_cost_reports_all_layouts():
    """cost_reports() harvests a usable model for the fused step AND
    the multi-dispatch program sequence (prescale/stripe/final)."""
    g = _graph()
    eng = make_engine("jax", PageRankConfig(
        num_iters=2, num_devices=2)).build(g)
    snap = eng.cost_reports()
    assert "step" in snap
    assert snap["step"]["num_edges"] == g.num_edges
    assert snap["step"]["bytes_per_edge"] is None or \
        snap["step"]["bytes_per_edge"] > 0
    # Repeat calls are served from the harvested flag (no recompile).
    assert eng.cost_reports() == snap

    class TinyScan(JaxTpuEngine):
        def _stripe_max(self):
            return 256

        def _stripe_target(self):
            return 256

        SCAN_STRIPE_UNITS = 0

    obs_costs.reset()
    ms = TinyScan(PageRankConfig(num_iters=2, num_devices=2)).build(g)
    assert ms._ms_stripe is not None
    snap_ms = ms.cost_reports()
    assert "prescale" in snap_ms and "final" in snap_ms
    assert any(k.startswith("stripe") for k in snap_ms)


def test_run_report_carries_costs_and_diff_renders(tmp_path):
    """run_report.json costs section + `obs report A B` diffing it —
    the code-regression-vs-backend-drift axis on the analytic model."""
    from pagerank_tpu.obs import report as report_mod
    from pagerank_tpu.obs.__main__ import main as obs_main

    compiled = jax.jit(lambda x: x * 3.0).lower(
        jnp.ones((64,), jnp.float32)
    ).compile()
    obs_costs.harvest("step", compiled, num_edges=64)
    a = report_mod.build_run_report()
    assert "costs" in a and "step" in a["costs"]
    pa = tmp_path / "a.json"
    report_mod.write_run_report(str(pa), a)

    obs_costs.reset()
    compiled2 = jax.jit(lambda x: (x * 3.0) + x).lower(
        jnp.ones((64,), jnp.float32)
    ).compile()
    obs_costs.harvest("step", compiled2, num_edges=64)
    b = report_mod.build_run_report()
    pb = tmp_path / "b.json"
    report_mod.write_run_report(str(pb), b)

    rendered = report_mod.render_report(a)
    assert "cost model" in rendered
    diff = report_mod.diff_reports(a, b)
    assert "cost-model" in diff or "cost model" in diff
    assert obs_main(["report", str(pa), str(pb)]) == 0


def test_bench_leg_costs_block():
    """bench.run_rate's costs block: the step form with an attached
    measurement (roofline fields None off the TPU table)."""
    import bench

    g = _graph()
    eng = make_engine("jax", PageRankConfig(
        num_iters=2, num_devices=2)).build(g)
    block, lowering = bench._leg_costs(eng, 0.01, g.num_edges)
    assert "step" in block
    assert block["step"]["seconds_per_iter"] == 0.01
    assert block["step"]["roofline_fraction"] is None  # CPU substrate
    b = block["step"]["bytes_per_edge"]
    assert b is None or b > 0
    # The compiler-plane block rides the same harvest (ISSUE 11):
    # same compiled handle, classified while the inspector was armed.
    assert lowering is None or \
        lowering["step"]["gather"]["strategy"] == "native"


# -- probed fused path ------------------------------------------------------


def test_fused_chunked_probe_boundaries():
    """Probes at fused-chunk boundaries: same cadence and churn
    telemetry as the stepwise loop's probe points."""
    g = _graph(seed=13)
    cfg = PageRankConfig(num_iters=8, num_devices=2)
    eng = make_engine("jax", cfg).build(g)
    probes = obs.ConvergenceProbes(4, topk=8)

    def on_chunk(done, ranks_thunk, traces):
        if done % probes.every == 0:
            probes.probe_boundary(
                eng, done - 1,
                l1_delta=float(jax.device_get(traces[0][-1])),
            )

    eng.run_fused_chunked(every=4, on_chunk=on_chunk)
    assert [r["iteration"] for r in probes.history] == [3, 7]

    # Parity vs stepwise probes on the same graph/config.
    eng2 = make_engine("jax", cfg).build(g)
    probes2 = obs.ConvergenceProbes(4, topk=8)
    eng2.run(probes=probes2)
    for a, b in zip(probes.history, probes2.history):
        assert a["iteration"] == b["iteration"]
        assert a["rank_mass"] == pytest.approx(b["rank_mass"])
        assert a["topk_churn"] == b["topk_churn"]


def test_fused_stop_tol_fires_at_probe_points_only(tmp_path):
    """--stop-tol under --fused with BOTH cadences set (gcd chunks):
    the stop check runs at probe boundaries only — a snapshot-only
    boundary must never early-exit the solve, matching the stepwise
    contract."""
    from pagerank_tpu.cli import main as cli_main

    report = tmp_path / "rr.json"
    rc = cli_main([
        "--synthetic", "uniform:400:3000", "--iters", "12",
        "--log-every", "0", "--fused",
        "--snapshot-dir", str(tmp_path / "ck"), "--snapshot-every", "3",
        "--probe-every", "2", "--stop-tol", "1e30",
        "--run-report", str(report),
    ])
    assert rc == 0
    rep = json.loads(report.read_text())
    # An absurdly loose tol stops at the FIRST probe point (iteration
    # 1, i.e. 2 iterations done) — not at the done=1 or done=3
    # snapshot-cadence boundaries the gcd chunking also visits.
    assert [r["iteration"] for r in rep["probes"]] == [1]
    assert rep["summary"]["iters"] == 2


def test_cli_probed_live_run(tmp_path):
    """End-to-end CLI: probes + textfile + watchdog (non-fire) + run
    report — the acceptance smoke H shape, as a tier-1 test."""
    from pagerank_tpu.cli import main as cli_main

    report = tmp_path / "rr.json"
    textfile = tmp_path / "metrics.prom"
    rc = cli_main([
        "--synthetic", "uniform:400:3000", "--engine", "cpu",
        "--iters", "6", "--log-every", "0",
        "--probe-every", "2", "--probe-topk", "16",
        "--metrics-textfile", str(textfile),
        "--stall-timeout", "300",
        "--run-report", str(report),
    ])
    assert rc == 0
    rep = json.loads(report.read_text())
    assert [r["iteration"] for r in rep["probes"]] == [1, 3, 5]
    probe_iters = [r for r in rep["iterations"] if "rank_mass" in r]
    assert [r["iter"] for r in probe_iters] == [1, 3, 5]
    assert all("topk_churn" in r for r in probe_iters)
    text = textfile.read_text()
    assert_prometheus_syntax(text)
    assert "pagerank_probe_points 3" in text
    assert "pagerank_solve_step_seconds_ms_count 6" in text
    # Watchdog armed and never fired.
    assert "watchdog.stalls" not in (
        rep["metrics"].get("counters") or {}
    )
    # The watchdog is disarmed after the run.
    assert obs.get_watchdog() is None
