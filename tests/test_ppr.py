"""Personalized PageRank tests (BASELINE config 5)."""

import numpy as np
import pytest

from pagerank_tpu import PageRankConfig, build_graph
from pagerank_tpu.engines.ppr import PprJaxEngine, ppr_cpu


def graph(seed=0, n=150, e=1200):
    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


def test_ppr_columns_are_distributions():
    g = graph()
    srcs = np.array([0, 5, 17])
    r = ppr_cpu(g, srcs, num_iters=50)
    np.testing.assert_allclose(r.sum(0), 1.0, atol=1e-9)
    assert np.all(r >= 0)


def test_ppr_localizes_at_source():
    # With damping 0.85 and a one-hot restart, the source should hold a
    # large share of its own rank mass.
    g = graph(seed=2)
    srcs = np.array([3])
    r = ppr_cpu(g, srcs, num_iters=50)
    assert r[3, 0] >= 0.15 - 1e-9  # at least the restart mass


def test_ppr_jax_matches_cpu_oracle():
    g = graph(seed=4)
    srcs = np.array([1, 7, 42, 99])
    expected = ppr_cpu(g, srcs, num_iters=25)
    cfg = PageRankConfig(num_iters=25, dtype="float64", accum_dtype="float64")
    eng = PprJaxEngine(cfg).build(g)
    res = eng.run(srcs, topk=g.n, chunk=3)  # chunk<len to test chunking
    # Reconstruct full vectors from topk=n results.
    for j in range(len(srcs)):
        full = np.zeros(g.n)
        full[res.topk_ids[j]] = res.topk_scores[j]
        np.testing.assert_allclose(full, expected[:, j], rtol=0, atol=1e-12)


def test_ppr_topk_ordering():
    g = graph(seed=6)
    eng = PprJaxEngine(PageRankConfig(num_iters=20)).build(g)
    res = eng.run(np.array([10]), topk=10)
    scores = res.topk_scores[0]
    assert np.all(np.diff(scores) <= 1e-12)  # descending


def test_ppr_uniform_dangling_mode():
    g = graph(seed=8)
    srcs = np.array([2])
    r = ppr_cpu(g, srcs, num_iters=30, dangling_to="uniform")
    assert r.shape == (g.n, 1)
    eng = PprJaxEngine(
        PageRankConfig(num_iters=30, dtype="float64", accum_dtype="float64"),
        dangling_to="uniform",
    ).build(g)
    res = eng.run(srcs, topk=g.n)
    full = np.zeros(g.n)
    full[res.topk_ids[0]] = res.topk_scores[0]
    np.testing.assert_allclose(full, r[:, 0], rtol=0, atol=1e-12)


def test_ppr_wide_accum_f32_storage():
    # f32 storage + f64 accumulation: the prescale multiply must carry
    # accum precision (per-edge products exact), keeping the iterates
    # well under plain-f32 error on a multi-stripe graph.
    g = graph(seed=12, n=400, e=4000)
    srcs = np.array([5, 250])
    expected = ppr_cpu(g, srcs, num_iters=20)
    cfg = PageRankConfig(num_iters=20, dtype="float32",
                         accum_dtype="float64")
    res = PprJaxEngine(cfg).build(g).run(srcs, topk=g.n)
    for j in range(len(srcs)):
        full = np.zeros(g.n)
        full[res.topk_ids[j]] = res.topk_scores[j]
        np.testing.assert_allclose(full, expected[:, j], rtol=0, atol=3e-7)


def test_ppr_multi_stripe():
    # Force >1 stripe by shrinking the stripe cap; results must match the
    # single-stripe/oracle answer exactly in f64.
    g = graph(seed=13, n=500, e=5000)
    srcs = np.array([7, 123, 480])
    expected = ppr_cpu(g, srcs, num_iters=15)

    class SmallStripe(PprJaxEngine):
        STRIPE = 128

    cfg = PageRankConfig(num_iters=15, dtype="float64",
                         accum_dtype="float64")
    res = SmallStripe(cfg).build(g).run(srcs, topk=g.n)
    for j in range(len(srcs)):
        full = np.zeros(g.n)
        full[res.topk_ids[j]] = res.topk_scores[j]
        np.testing.assert_allclose(full, expected[:, j], rtol=0, atol=1e-12)
