"""Personalized PageRank tests (BASELINE config 5)."""

import numpy as np
import pytest

from pagerank_tpu import PageRankConfig, build_graph
from pagerank_tpu.engines.ppr import PprJaxEngine, ppr_cpu


def graph(seed=0, n=150, e=1200):
    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


def test_ppr_columns_are_distributions():
    g = graph()
    srcs = np.array([0, 5, 17])
    r = ppr_cpu(g, srcs, num_iters=50)
    np.testing.assert_allclose(r.sum(0), 1.0, atol=1e-9)
    assert np.all(r >= 0)


def test_ppr_localizes_at_source():
    # With damping 0.85 and a one-hot restart, the source should hold a
    # large share of its own rank mass.
    g = graph(seed=2)
    srcs = np.array([3])
    r = ppr_cpu(g, srcs, num_iters=50)
    assert r[3, 0] >= 0.15 - 1e-9  # at least the restart mass


def test_ppr_jax_matches_cpu_oracle():
    g = graph(seed=4)
    srcs = np.array([1, 7, 42, 99])
    expected = ppr_cpu(g, srcs, num_iters=25)
    cfg = PageRankConfig(num_iters=25, dtype="float64", accum_dtype="float64")
    eng = PprJaxEngine(cfg).build(g)
    res = eng.run(srcs, topk=g.n, chunk=3)  # chunk<len to test chunking
    # Reconstruct full vectors from topk=n results.
    for j in range(len(srcs)):
        full = np.zeros(g.n)
        full[res.topk_ids[j]] = res.topk_scores[j]
        np.testing.assert_allclose(full, expected[:, j], rtol=0, atol=1e-12)


def test_ppr_topk_ordering():
    g = graph(seed=6)
    eng = PprJaxEngine(PageRankConfig(num_iters=20)).build(g)
    res = eng.run(np.array([10]), topk=10)
    scores = res.topk_scores[0]
    assert np.all(np.diff(scores) <= 1e-12)  # descending


def test_ppr_uniform_dangling_mode():
    g = graph(seed=8)
    srcs = np.array([2])
    r = ppr_cpu(g, srcs, num_iters=30, dangling_to="uniform")
    assert r.shape == (g.n, 1)
    eng = PprJaxEngine(
        PageRankConfig(num_iters=30, dtype="float64", accum_dtype="float64"),
        dangling_to="uniform",
    ).build(g)
    res = eng.run(srcs, topk=g.n)
    full = np.zeros(g.n)
    full[res.topk_ids[0]] = res.topk_scores[0]
    np.testing.assert_allclose(full, r[:, 0], rtol=0, atol=1e-12)


def test_ppr_wide_accum_f32_storage():
    # f32 storage + f64 accumulation: the prescale multiply must carry
    # accum precision (per-edge products exact), keeping the iterates
    # well under plain-f32 error on a multi-stripe graph.
    g = graph(seed=12, n=400, e=4000)
    srcs = np.array([5, 250])
    expected = ppr_cpu(g, srcs, num_iters=20)
    cfg = PageRankConfig(num_iters=20, dtype="float32",
                         accum_dtype="float64")
    res = PprJaxEngine(cfg).build(g).run(srcs, topk=g.n)
    for j in range(len(srcs)):
        full = np.zeros(g.n)
        full[res.topk_ids[j]] = res.topk_scores[j]
        np.testing.assert_allclose(full, expected[:, j], rtol=0, atol=3e-7)


def _oracle_full(g, srcs, num_iters=20):
    """f64 oracle vectors, one column per source."""
    return ppr_cpu(g, np.asarray(srcs), num_iters=num_iters)


def _f64_engine(g, num_iters=20, **kw):
    cfg = PageRankConfig(num_iters=num_iters, dtype="float64",
                         accum_dtype="float64", **kw)
    return PprJaxEngine(cfg).build(g)


def test_ppr_topk_k_clamped_to_n():
    # k >= n must clamp to n and still return every vertex exactly once
    # with oracle scores (the serving layer clamps the same way).
    g = graph(seed=20, n=60, e=500)
    srcs = np.array([3])
    expected = _oracle_full(g, srcs)
    res = _f64_engine(g).run(srcs, topk=10 * g.n)
    assert res.topk_ids.shape == (1, g.n)
    assert sorted(res.topk_ids[0].tolist()) == list(range(g.n))
    full = np.zeros(g.n)
    full[res.topk_ids[0]] = res.topk_scores[0]
    np.testing.assert_allclose(full, expected[:, 0], rtol=0, atol=1e-12)


def test_ppr_topk_tied_scores():
    # A bidirectional 4-cycle with restart at one vertex: the two
    # neighbors of the source are exactly symmetric, so their scores tie
    # EXACTLY in f64. The top-k must return both tied ids with bit-equal
    # scores and keep the score ordering descending.
    fwd_src, fwd_dst = np.array([0, 1, 2, 3]), np.array([1, 2, 3, 0])
    src = np.concatenate([fwd_src, fwd_dst])
    dst = np.concatenate([fwd_dst, fwd_src])
    g = build_graph(src, dst, n=4)
    expected = _oracle_full(g, [0], num_iters=30)[:, 0]
    res = _f64_engine(g, num_iters=30).run(np.array([0]), topk=4)
    ids, scores = res.topk_ids[0], res.topk_scores[0]
    assert np.all(np.diff(scores) <= 0)
    np.testing.assert_allclose(scores, expected[ids], rtol=0, atol=1e-12)
    # vertices 1 and 3 are symmetric around the source: exact tie in
    # the oracle AND bit-equal in the engine's own output.
    assert expected[1] == expected[3]
    assert {1, 3} <= set(ids.tolist())
    by_id = dict(zip(ids.tolist(), scores.tolist()))
    assert by_id[1] == by_id[3]


def test_ppr_dangling_heavy_graph():
    # Most vertices dangling (no out-edges): the dangling-mass term
    # dominates the update, in BOTH dangling policies.
    rng = np.random.default_rng(21)
    n = 120
    src = rng.integers(0, 10, 400)  # only vertices 0..9 have out-edges
    dst = rng.integers(0, n, 400)
    g = build_graph(src, dst, n=n)
    assert (g.out_degree == 0).sum() >= n - 10
    srcs = np.array([4, 57])
    for mode in ("source", "uniform"):
        expected = ppr_cpu(g, srcs, num_iters=25, dangling_to=mode)
        cfg = PageRankConfig(num_iters=25, dtype="float64",
                             accum_dtype="float64")
        res = PprJaxEngine(cfg, dangling_to=mode).build(g).run(
            srcs, topk=g.n
        )
        for j in range(len(srcs)):
            full = np.zeros(g.n)
            full[res.topk_ids[j]] = res.topk_scores[j]
            np.testing.assert_allclose(
                full, expected[:, j], rtol=0, atol=1e-12
            )


def test_ppr_batch_with_repeated_source():
    # The same source twice in one batch (the serving batcher pads with
    # repeats): both lanes must produce identical answers, equal to the
    # lane of a batch where it appears once.
    g = graph(seed=22)
    res = _f64_engine(g).run(np.array([9, 9, 40]), topk=20)
    np.testing.assert_array_equal(res.topk_ids[0], res.topk_ids[1])
    np.testing.assert_allclose(
        res.topk_scores[0], res.topk_scores[1], rtol=0, atol=0
    )
    solo = _f64_engine(g).run(np.array([9]), topk=20)
    np.testing.assert_array_equal(res.topk_ids[0], solo.topk_ids[0])
    np.testing.assert_allclose(
        res.topk_scores[0], solo.topk_scores[0], rtol=0, atol=0
    )
    expected = _oracle_full(g, [9])[:, 0]
    np.testing.assert_allclose(
        res.topk_scores[0], expected[res.topk_ids[0]], rtol=0, atol=1e-12
    )


def test_ppr_multi_stripe():
    # Force >1 stripe by shrinking the stripe cap; results must match the
    # single-stripe/oracle answer exactly in f64.
    g = graph(seed=13, n=500, e=5000)
    srcs = np.array([7, 123, 480])
    expected = ppr_cpu(g, srcs, num_iters=15)

    class SmallStripe(PprJaxEngine):
        STRIPE = 128

    cfg = PageRankConfig(num_iters=15, dtype="float64",
                         accum_dtype="float64")
    res = SmallStripe(cfg).build(g).run(srcs, topk=g.n)
    for j in range(len(srcs)):
        full = np.zeros(g.n)
        full[res.topk_ids[j]] = res.topk_scores[j]
        np.testing.assert_allclose(full, expected[:, j], rtol=0, atol=1e-12)
