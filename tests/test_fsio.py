"""Pluggable URI-scheme I/O (utils/fsio) — the seam standing in for the
reference's Hadoop S3 filesystem (s3n:// inputs Sparky.java:44-58, S3
output :237). A mock:// object store must round-trip every loader and
sink: ingest -> snapshot -> resume -> final ranks through the CLI."""

import json

import numpy as np
import pytest

from pagerank_tpu import PageRankConfig, ReferenceCpuEngine, build_graph
from pagerank_tpu.cli import main
from pagerank_tpu.utils import fsio


@pytest.fixture
def mockfs():
    fs = fsio.MemoryFileSystem()
    fsio.register("mock", fs)
    yield fs
    fsio.unregister("mock")


def test_scheme_parsing():
    assert fsio.scheme_of("s3n://bucket/key") == "s3n"
    assert fsio.scheme_of("mock://x") == "mock"
    assert fsio.scheme_of("/local/path") is None
    assert fsio.scheme_of("relative/path") is None
    assert fsio.scheme_of("edges.txt") is None


def test_unregistered_scheme_error_is_actionable():
    with pytest.raises(ValueError, match="no filesystem registered.*s3n"):
        fsio.fopen("s3n://bucket/metadata-00000", "rb")


def test_memory_fs_basics(mockfs):
    with fsio.fopen("mock://b/dir/a.txt", "w") as f:
        f.write("hello")
    assert fsio.exists("mock://b/dir/a.txt")
    assert fsio.isfile("mock://b/dir/a.txt")
    assert fsio.isdir("mock://b/dir")
    assert not fsio.isdir("mock://b/dir/a.txt")
    with fsio.fopen("mock://b/dir/a.txt") as f:
        assert f.read() == "hello"
    with fsio.fopen("mock://b/dir/a.txt", "a") as f:
        f.write(" world")
    with fsio.fopen("mock://b/dir/a.txt", "rb") as f:
        assert f.read() == b"hello world"
    with pytest.raises(FileNotFoundError):
        fsio.fopen("mock://b/missing", "rb")
    # one-level listing, object-store style
    with fsio.fopen("mock://b/dir/sub/c.bin", "wb") as f:
        f.write(b"\x00\x01")
    assert fsio.listdir("mock://b/dir") == ["a.txt", "sub"]
    fsio.replace("mock://b/dir/a.txt", "mock://b/dir/b.txt")
    assert not fsio.exists("mock://b/dir/a.txt")
    assert fsio.listdir("mock://b/dir") == ["b.txt", "sub"]


def test_replace_rejects_cross_scheme(mockfs, tmp_path):
    # A cross-scheme replace would silently rename INSIDE src's store,
    # minting a key spelled with the other scheme (ADVICE r2).
    local = tmp_path / "x.bin"
    local.write_bytes(b"z")
    with pytest.raises(ValueError, match="same-store"):
        fsio.replace(str(local), "mock://b/x.bin")
    with pytest.raises(ValueError, match="same-store"):
        fsio.replace("mock://b/x.bin", str(local))
    assert not fsio.exists("mock://b/x.bin")
    assert local.exists()


def test_join_preserves_schemes(tmp_path):
    # Scheme paths join with literal '/' regardless of OS separator, and
    # a leading-'/' part must not discard the base (ADVICE r2).
    assert fsio.join("mock://b/dir", "a", "b.txt") == "mock://b/dir/a/b.txt"
    assert fsio.join("mock://b/dir/", "/a.txt") == "mock://b/dir/a.txt"
    # A bare scheme root keeps its '//' (stripping it would silently
    # produce a local path).
    assert fsio.join("mock://", "bucket/key") == "mock://bucket/key"
    # Local paths keep os.path.join semantics.
    import os

    assert fsio.join(str(tmp_path), "a") == os.path.join(str(tmp_path), "a")


def test_memwriter_commits_on_flush(mockfs):
    # Incremental sinks (JSONL metrics) flush per record; a killed run
    # must keep flushed records like the local backend does (ADVICE r2).
    f = fsio.fopen("mock://b/m.jsonl", "a")
    f.write('{"iter": 0}\n')
    f.flush()
    with fsio.fopen("mock://b/m.jsonl") as r:
        assert r.read() == '{"iter": 0}\n'
    f.write('{"iter": 1}\n')
    # NOT flushed: record 1 is only visible after close (and would be
    # lost on a kill — matching an unflushed local buffer).
    with fsio.fopen("mock://b/m.jsonl") as r:
        assert r.read() == '{"iter": 0}\n'
    f.close()
    with fsio.fopen("mock://b/m.jsonl") as r:
        assert r.read() == '{"iter": 0}\n{"iter": 1}\n'


def test_memory_fs_write_is_atomic_on_close(mockfs):
    f = fsio.fopen("mock://b/partial", "wb")
    f.write(b"data")
    # Not visible until close — object-store PUT semantics.
    assert not fsio.exists("mock://b/partial")
    f.close()
    assert fsio.exists("mock://b/partial")


@pytest.mark.parametrize("mode", ["wb", "w"])
def test_memory_fs_aborts_put_on_with_block_exception(mockfs, mode):
    # A writer that dies mid-serialization must not publish a torn
    # object (a real store abandons the upload).
    payload = b"torn" if mode == "wb" else "torn"
    with pytest.raises(RuntimeError):
        with fsio.fopen("mock://b/torn", mode) as f:
            f.write(payload)
            raise RuntimeError("dies mid-write")
    assert not fsio.exists("mock://b/torn")


def test_windows_drive_syntax_is_not_a_scheme():
    assert fsio.scheme_of("C://data/edges.txt") is None
    assert fsio.registered(None)
    assert not fsio.registered("s3n")


def _edges_to(uri, rng, n=50, e=300):
    src, dst = rng.integers(0, n, e), rng.integers(0, n, e)
    with fsio.fopen(uri, "w") as f:
        for s, d in zip(src, dst):
            f.write(f"{s} {d}\n")
    return src, dst


def test_cli_mock_scheme_ingest_snapshot_resume_roundtrip(mockfs):
    """VERDICT r1 item 4 'done' criterion: a registered mock scheme
    round-trips ingest -> snapshot -> resume, all storage on the mock
    store (plus the final ranks sink)."""
    rng = np.random.default_rng(0)
    src, dst = _edges_to("mock://in/edges.txt", rng)

    rc = main(["--input", "mock://in/edges.txt", "--iters", "3",
               "--snapshot-dir", "mock://ckpt", "--sync-io",
               "--log-every", "0"])
    assert rc == 0
    assert "ranks_iter3.npz" in fsio.listdir("mock://ckpt")
    # No torn temp objects left behind.
    assert not any(n.endswith(".tmp.npz") for n in fsio.listdir("mock://ckpt"))

    rc = main(["--input", "mock://in/edges.txt", "--iters", "6",
               "--snapshot-dir", "mock://ckpt", "--resume",
               "--out", "mock://out/r.tsv", "--log-every", "0"])
    assert rc == 0

    g = build_graph(src, dst)
    expected = ReferenceCpuEngine(PageRankConfig(num_iters=6)).build(g).run()
    got = np.zeros(g.n)
    with fsio.fopen("mock://out/r.tsv") as f:
        for line in f:
            k, v = line.split("\t")
            got[int(k)] = float(v)
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)


def test_cli_mock_scheme_crawl_and_text_dump(mockfs):
    meta = json.dumps(
        {"content": {"links": [{"href": "http://b", "type": "a"}]}}
    )
    with fsio.fopen("mock://in/crawl.tsv", "w") as f:
        f.write(f"http://a\t{meta}\nhttp://b\t{json.dumps({})}\n")
    rc = main(["--input", "mock://in/crawl.tsv", "--iters", "2",
               "--engine", "cpu", "--dump-text-dir", "mock://dumps",
               "--log-every", "0"])
    assert rc == 0
    assert fsio.listdir("mock://dumps") == ["PageRank0", "PageRank1"]
    names = fsio.listdir("mock://dumps/PageRank1")
    assert names == ["_SUCCESS", "part-00000"]
    with fsio.fopen("mock://dumps/PageRank1/part-00000") as f:
        body = f.read()
    assert "(http://a," in body and "(http://b," in body


def test_seqfile_roundtrip_over_mock_scheme(mockfs):
    from pagerank_tpu.ingest import read_sequence_file, write_sequence_file

    meta = json.dumps(
        {"content": {"links": [{"href": "http://t", "type": "a"}]}}
    )
    pairs = [(f"http://u{i}", meta) for i in range(10)]
    fsio.makedirs("mock://seg")
    write_sequence_file("mock://seg/metadata-00000", pairs, sync_every=4)
    back = list(read_sequence_file("mock://seg/metadata-00000"))
    assert back == pairs
    # Directory expansion over the mock scheme (segment-dir input form).
    rc = main(["--input", "mock://seg", "--iters", "2", "--engine", "cpu",
               "--log-every", "0"])
    assert rc == 0


def test_binary_edges_roundtrip_over_mock_scheme(mockfs):
    from pagerank_tpu.ingest import load_binary_edges, save_binary_edges

    src = np.array([0, 1, 2], np.int64)
    dst = np.array([1, 2, 0], np.int64)
    save_binary_edges("mock://b/edges.npz", src, dst, n=3)
    s, d, n = load_binary_edges("mock://b/edges.npz")
    assert n == 3
    np.testing.assert_array_equal(s, src)
    np.testing.assert_array_equal(d, dst)
