"""A pure-Python, dict-based transliteration of the reference's RDD
pipeline (`Sparky.java:78-238`), quirks included — the golden oracle the
vectorized engines are diffed against (SURVEY.md §4).

This deliberately mimics the *structure* of the Spark program (flatMap →
distinct → groupByKey → join → subtractByKey → reduceByKey), not good
Python, so each line can be matched to a Sparky.java line.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

Record = Tuple[str, List[str]]  # (url, anchor targets from one crawl record)


def sparky_pagerank(
    records: Iterable[Record],
    num_iters: int = 10,
    damping: float = 0.85,
):
    """Run the reference pipeline on (url, targets) records.

    A record with an empty target list is a crawled page with no anchor
    links — it emits the (url, null) sentinel and joins dangUrls
    (Sparky.java:114-118).

    Returns (ranks, history, all_urls, dangling) where history[i] is the
    rank dict the reference would write to S3 after iteration i
    (Sparky.java:237).
    """
    # flatMapToPair with dangling sentinel (Sparky.java:78-123)
    edges = set()
    dang = set()
    for url, targets in records:
        if targets:  # isDangling=false iff >=1 anchor link (Sparky.java:103-106)
            for t in targets:
                edges.add((url, t))  # .distinct() dedups (Sparky.java:124)
        else:
            edges.add((url, None))
            dang.add(url)

    # groupByKey (Sparky.java:124)
    adj: Dict[str, List[Optional[str]]] = {}
    for s, t in sorted(edges, key=lambda e: (e[0], e[1] is None, e[1] or "")):
        adj.setdefault(s, []).append(t)

    # keys().collect() + broadcast (Sparky.java:127-135)
    keyset = set(adj)

    # graph completion: uncrawled targets -> (target, null), distinct
    # (Sparky.java:137-159); union (Sparky.java:161)
    completion = set()
    for s, ts in adj.items():
        for t in ts:
            if t is not None and t not in keyset:
                dang.add(t)
                completion.add(t)
    all_urls: Dict[str, Optional[List[Optional[str]]]] = dict(adj)
    for t in completion:
        all_urls[t] = None

    n = len(all_urls)  # totalUrlCount (Sparky.java:162)
    ranks = {u: 1.0 for u in all_urls}  # init to 1.0 (Sparky.java:165-170)

    # dangling repair pass (Sparky.java:172-184). lookup(s) returns the
    # *list of values* for key s, so for any crawled page get(0) is its
    # (non-null) grouped Iterable — even when that Iterable is [null].
    # The size==1 && get(0)==null test therefore only matches uncrawled
    # targets, whose stored value is literally null (Sparky.java:149):
    # the repair removes EVERY crawled page from dangUrls.
    not_dangling = set()
    for s in dang:
        lookup = [all_urls[s]]  # List<Iterable<String>> with one element
        if not (len(lookup) == 1 and lookup[0] is None):
            not_dangling.add(s)
    dang -= not_dangling

    history = []
    for _ in range(num_iters):
        # contribution scatter (Sparky.java:192-216)
        contribs: Dict[str, List[float]] = {}
        for u, a in all_urls.items():  # join(ranks).values()
            if a is not None:
                url_count = len(a) - sum(1 for x in a if x is None)
                if url_count > 0:
                    page_rank = ranks[u] / url_count
                    for t in a:
                        if t is not None:
                            contribs.setdefault(t, []).append(page_rank)
        # dangling mass via per-url lookup (Sparky.java:219-222)
        dangling_contrib = sum(ranks[u] for u in dang)
        # subtractByKey + union: missing keys keep old rank (Sparky.java:224-225)
        for u in ranks:
            if u not in contribs:
                contribs[u] = [ranks[u]]
        # reduceByKey(Sum) + update (Sparky.java:229-235; the reference
        # hardcodes 0.15/0.85 — parameterized here as (1-d)/d so the
        # constant stays consistent with the engines at any damping)
        ranks = {
            u: (1.0 - damping) + damping * (sum(c) + dangling_contrib / n)
            for u, c in contribs.items()
        }
        history.append(dict(ranks))
    return ranks, history, all_urls, dang
