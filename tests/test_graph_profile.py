"""Data-plane observability (ISSUE 13; pagerank_tpu/obs/graph_profile.py).

Four gated axes:
  - every GraphProfile stat matches an INDEPENDENT numpy oracle on
    random + R-MAT inputs (device-build fused pass AND host numpy);
  - the rank-mass ledger sums to 1 (textbook) / reconciles (reference)
    within dtype tolerance across the dispatch forms — incl. vs_halo
    and partitioned — and names the leaking term when mass breaks;
  - a DISARMED run makes zero profile computations and is bit-identical
    (the tracer/sampler booby-trap discipline);
  - predicted per-device load agrees with the measured per-device edge
    counts on the 8-fake-device mesh within 10%, and the job artifact
    round-trips with tamper rejection.
"""

import json
import os

import jax
import numpy as np
import pytest

from pagerank_tpu import PageRankConfig, build_graph
from pagerank_tpu.engine import SolverHealthError, make_engine
from pagerank_tpu.obs import graph_profile
from pagerank_tpu.obs.probes import ConvergenceProbes
from pagerank_tpu.ops import device_build as db
from pagerank_tpu.parallel import comms
from pagerank_tpu.utils.synth import rmat_edges, uniform_edges

NDEV = len(jax.devices())
needs_mesh = pytest.mark.skipif(NDEV < 8, reason="needs 8 fake devices")


@pytest.fixture(autouse=True)
def _clean_profile_state():
    graph_profile.reset()
    graph_profile.disarm()
    yield
    graph_profile.reset()
    graph_profile.disarm()


# -- independent numpy oracle ------------------------------------------------


def _oracle_hist(deg):
    bins = np.zeros(graph_profile.HIST_BINS, np.int64)
    for d in np.asarray(deg, np.int64):
        bins[int(d).bit_length()] += 1
    return bins


def _oracle_profile(raw_src, raw_dst, n, sz, group):
    """Every profile stat recomputed from FIRST PRINCIPLES (np.unique
    dedup, bit_length histogram, explicit run-length row packing) —
    deliberately not sharing code with the module under test."""
    raw_src = np.asarray(raw_src, np.int64)
    raw_dst = np.asarray(raw_dst, np.int64)
    key = raw_dst * n + raw_src
    uk = np.unique(key)
    src = (uk % n).astype(np.int64)
    dst = (uk // n).astype(np.int64)
    in_deg = np.bincount(dst, minlength=n)
    out_deg = np.bincount(src, minlength=n)
    raw_in = np.bincount(raw_dst, minlength=n)
    # The build relabels by RAW in-degree, stable descending.
    perm = np.argsort(-raw_in, kind="stable")
    inv = np.empty(n, np.int64)
    inv[perm] = np.arange(n)
    n_padded = -(-n // 128) * 128
    span = sz or n_padded
    n_stripes = -(-n_padded // span)
    new_src, new_dst = inv[src], inv[dst]
    out = {
        "num_edges": uk.size,
        "raw_edges": raw_src.size,
        "self_loops": int((src == dst).sum()),
        "dangling_count": int((out_deg == 0).sum()),
        "zero_in_count": int((in_deg == 0).sum()),
        "in_hist": _oracle_hist(in_deg),
        "out_hist": _oracle_hist(out_deg),
        "partition_edges": np.bincount(new_src // span,
                                       minlength=n_stripes),
        "block_edges": np.bincount(
            (new_src // span) * (n_padded // 128) + new_dst // 128,
            minlength=n_stripes * (n_padded // 128)),
        "in_deg_rel": in_deg[perm],
    }
    # Rows per (stripe, block): run lengths over the RAW relabeled
    # sorted order (duplicate edges occupy slots too), max over lane
    # groups of ceil(run/group) — first-principles walk.
    rs, rd = inv[raw_src], inv[raw_dst]
    order = np.lexsort((rs, rd, rs // span))
    rs, rd = rs[order], rd[order]
    log2g = group.bit_length() - 1
    grp = ((rs // span) * n_padded + rd) >> log2g
    rows = {}
    from collections import Counter

    for g_id, cnt in Counter(grp.tolist()).items():
        sb = ((g_id << log2g) // n_padded) * (n_padded // 128) + (
            (g_id << log2g) % n_padded) // 128
        rows[sb] = max(rows.get(sb, 0), -(-cnt // group))
    block_rows = np.zeros(n_stripes * (n_padded // 128), np.int64)
    for sb, r in rows.items():
        block_rows[sb] = r
    out["block_rows"] = block_rows
    return out


def _check_profile(prof, oracle):
    assert prof.num_edges == oracle["num_edges"]
    if prof.raw_edges is not None:
        assert prof.raw_edges == oracle["raw_edges"]
    assert prof.self_loops == oracle["self_loops"]
    assert prof.dangling_count == oracle["dangling_count"]
    assert prof.zero_in_count == oracle["zero_in_count"]
    assert np.array_equal(prof.in_hist, oracle["in_hist"])
    assert np.array_equal(prof.out_hist, oracle["out_hist"])
    assert np.array_equal(prof.partition_edges,
                          oracle["partition_edges"])
    assert np.array_equal(prof.block_edges, oracle["block_edges"])
    if prof.block_rows is not None:
        assert np.array_equal(prof.block_rows, oracle["block_rows"])
    # Top hubs: the DEGREES must be the k largest unique in-degrees,
    # and each returned id must carry its claimed degree (id-level
    # equality is tie-dependent, degree-level is not).
    want = np.sort(oracle["in_deg_rel"])[::-1][:len(prof.top_hub_ids)]
    assert np.array_equal(prof.top_hub_in_degrees, want)


@pytest.mark.parametrize("gen,seed", [("rmat", 0), ("uniform", 7)])
def test_device_profile_matches_numpy_oracle(gen, seed):
    scale, n = 10, 1 << 10
    if gen == "rmat":
        sd, dd = db.rmat_edges_device(scale, seed=seed)
    else:
        sd, dd = db.uniform_edges_device(n, 16 * n, seed=seed)
    raw_src = np.asarray(jax.device_get(sd))
    raw_dst = np.asarray(jax.device_get(dd))
    group, sz = 4, 256
    graph_profile.arm()
    dg = db.build_ell_device(raw_src.copy(), raw_dst.copy(), n=n,
                             group=group, stripe_size=sz)
    prof = graph_profile.get_profile()
    assert prof is not None and prof.source == "device_build"
    assert prof.fingerprint == dg.fingerprint()
    _check_profile(prof, _oracle_profile(raw_src, raw_dst, n, sz, group))
    # Hub ids claim their degrees in ORIGINAL id space.
    key = raw_dst.astype(np.int64) * n + raw_src
    dst_u = np.unique(key) // n
    in_deg = np.bincount(dst_u, minlength=n)
    for vid, d in zip(prof.top_hub_ids, prof.top_hub_in_degrees):
        assert in_deg[vid] == d


def test_host_profile_matches_numpy_oracle():
    n = 1 << 10
    src, dst = rmat_edges(10, 16, seed=3)
    g = build_graph(src, dst, n=n)
    prof = graph_profile.profile_graph(g, partition_span=256, group=4)
    oracle = _oracle_profile(np.asarray(g.src), np.asarray(g.dst), n,
                             256, 4)
    _check_profile(prof, oracle)
    assert prof.raw_edges is None and prof.duplicate_edges is None
    assert prof.fingerprint == g.fingerprint()
    # Host and device paths agree on the shared stats when fed the
    # SAME deduplicated edges.
    graph_profile.arm()
    db.build_ell_device(np.asarray(g.src).copy(),
                        np.asarray(g.dst).copy(), n=n, group=4,
                        stripe_size=256)
    dev = graph_profile.get_profile()
    assert dev.num_edges == prof.num_edges
    assert dev.in_hist == prof.in_hist
    assert dev.out_hist == prof.out_hist
    assert dev.partition_edges == prof.partition_edges
    assert np.array_equal(dev.block_edges, prof.block_edges)
    assert np.array_equal(dev.block_rows, prof.block_rows)
    assert dev.top_hub_in_degrees == prof.top_hub_in_degrees


def test_log2_hist_is_bit_length_exact():
    deg = np.array([0, 1, 2, 3, 4, 7, 8, 1023, 1024, (1 << 24) + 1,
                    (1 << 30) + 5])
    assert np.array_equal(graph_profile.log2_hist(deg),
                          _oracle_hist(deg))


def test_powerlaw_alpha_recovers_synthetic_exponent():
    # Exact power-law histogram: count in bin k = C * 2^(k(1-alpha)).
    alpha = 2.2
    hist = [0, 0] + [int(round(1e6 * 2 ** (k * (1 - alpha))))
                     for k in range(2, 12)]
    hist += [0] * (graph_profile.HIST_BINS - len(hist))
    prof = graph_profile.GraphProfile(
        n=10, n_padded=128, num_edges=10, raw_edges=None,
        self_loops=None, dangling_count=0, zero_in_count=0,
        in_hist=hist, out_hist=hist, top_hub_ids=[], top_hub_in_degrees=[],
        partition_edges=[10], stripe_span=0)
    assert prof.powerlaw_alpha() == pytest.approx(alpha, abs=0.05)


# -- the rank-mass ledger ----------------------------------------------------


def _run_probed(engine_name, graph, semantics="textbook", iters=4,
                **cfg_kw):
    cfg = PageRankConfig(num_iters=iters, semantics=semantics,
                         probe_every=1, **cfg_kw)
    eng = make_engine(engine_name, cfg).build(graph)
    probes = ConvergenceProbes(1, topk=32)
    eng.run(probes=probes)
    return eng, probes


LEDGER_FORMS = [
    ("cpu", {}),
    ("jax", {}),                                  # default ell
    ("jax", dict(kernel="coo")),
    ("jax", dict(partition_span=512)),            # partitioned
    pytest.param("jax", dict(vertex_sharded=True, num_devices=8),
                 marks=needs_mesh, id="jax-vs_dense"),
    pytest.param("jax", dict(vertex_sharded=True, halo_exchange=True,
                             num_devices=8),
                 marks=needs_mesh, id="jax-vs_halo"),
    pytest.param("jax", dict(vertex_sharded=True, vs_bounded=True,
                             num_devices=8),
                 marks=needs_mesh, id="jax-vs_bounded"),
]


@pytest.mark.parametrize("engine_name,kw", LEDGER_FORMS)
def test_ledger_sums_to_one_across_forms(engine_name, kw):
    g = build_graph(*rmat_edges(11, 16, seed=2), n=1 << 11)
    eng, probes = _run_probed(engine_name, g, **kw)
    assert len(probes.history) == 4
    tol = graph_profile.ledger_tolerance(eng._ledger_eps(), g.n)
    for rec in probes.history:
        ml = rec["mass_ledger"]
        assert ml is not None and ml["ok"], ml
        assert ml["leak"] is None
        # Textbook mass is conserved at 1 — the decomposition's terms
        # sum to the measured mass AND the mass is the unit.
        assert abs(ml["normalized_mass"] - 1.0) <= 4 * tol + 1e-6
        assert abs(ml["residual"]) <= tol
        assert abs(ml["teleport_mass"] + ml["link_mass"]
                   + ml["retained_mass"] + ml["dangling_mass"]
                   - ml["normalized_mass"]) <= tol
    assert probes.ledger_violations == []


def test_ledger_multi_dispatch_form():
    """Striped device graph past SCAN_STRIPE_UNITS: the ledger rides
    the dedicated _ms_final_ledger executable."""
    src, dst = rmat_edges(11, 16, seed=4)
    g = build_graph(src, dst, n=1 << 11)
    dg = db.build_ell_device(np.asarray(g.src).copy(),
                             np.asarray(g.dst).copy(), n=g.n,
                             stripe_size=128)
    cfg = PageRankConfig(num_iters=3, semantics="textbook",
                         probe_every=1)
    eng = make_engine("jax", cfg).build_device(dg)
    assert eng._ms_stripe is not None  # the multi-dispatch form engaged
    probes = ConvergenceProbes(1, topk=16)
    eng.run(probes=probes)
    tol = graph_profile.ledger_tolerance(eng._ledger_eps(), g.n)
    for rec in probes.history:
        ml = rec["mass_ledger"]
        assert ml["ok"] and abs(ml["residual"]) <= tol


def test_ledger_reference_semantics_identity():
    """Reference semantics deliberately does not conserve mass (the
    zero-in retention); the ledger still reconciles its IDENTITY —
    measured mass equals the term sum — with the retained term live."""
    g = build_graph(*rmat_edges(10, 16, seed=5), n=1 << 10)
    for engine_name in ("cpu", "jax"):
        _eng, probes = _run_probed(engine_name, g,
                                   semantics="reference", iters=3)
        for rec in probes.history:
            ml = rec["mass_ledger"]
            assert ml["ok"], ml
            assert ml["unaccounted"] is None  # no flow check here
            assert ml["retained_mass"] > 0


def test_probe_topk_concentration_recorded():
    g = build_graph(*rmat_edges(10, 16, seed=6), n=1 << 10)
    for engine_name in ("cpu", "jax"):
        _eng, probes = _run_probed(engine_name, g, iters=2)
        for rec in probes.history:
            assert 0.0 < rec["topk_concentration"] <= 1.0
    # cpu and jax agree on the concentration (parity like rank_mass).
    g2 = build_graph(*rmat_edges(10, 16, seed=6), n=1 << 10)
    _e1, p1 = _run_probed("cpu", g2, iters=2)
    _e2, p2 = _run_probed("jax", g2, iters=2)
    for a, b in zip(p1.history, p2.history):
        assert a["topk_concentration"] == pytest.approx(
            b["topk_concentration"], rel=1e-5)


def test_mass_ledger_entry_names_each_leak():
    """Unit coverage of the leak taxonomy (obs/graph_profile
    docstring): link (edges created mass), dangling (mass fell out of
    the flow), teleport (the epilogue's derived term broke)."""
    base = dict(damping=0.85, semantics="textbook", n=1000,
                eps=np.finfo(np.float32).eps, mass_prev=1.0)
    # Healthy: contrib == mass_prev - m.
    ok = graph_profile.mass_ledger_entry(
        mass=1.0, dangling_mass=0.2, contrib_total=0.8, **base)
    assert ok["ok"] and ok["leak"] is None
    # Edges CREATED mass (bad weights): unaccounted < 0 -> link.
    e = graph_profile.mass_ledger_entry(
        mass=1.0 + 0.85 * 0.1, dangling_mass=0.2, contrib_total=0.9,
        **base)
    assert e["leak"] == "link" and not e["ok"]
    # Mass fell out of the flow (a sink the mask misses) -> dangling.
    e = graph_profile.mass_ledger_entry(
        mass=1.0 - 0.85 * 0.1, dangling_mass=0.1, contrib_total=0.8,
        **base)
    assert e["leak"] == "dangling"
    # The identity itself broke (epilogue/mask) -> teleport.
    e = graph_profile.mass_ledger_entry(
        mass=0.9, dangling_mass=0.2, contrib_total=0.8, **base)
    assert e["leak"] == "teleport"


def test_health_error_names_leaking_term():
    """The ISSUE-13 satellite: engine.rank_mass()'s drift check routed
    through the ledger — SolverHealthError names WHICH term leaked. A
    scaled CSR (weights * 1.15) makes the oracle's edges CREATE mass:
    a link leak by construction."""
    g = build_graph(*rmat_edges(9, 16, seed=7), n=1 << 9)
    cfg = PageRankConfig(
        num_iters=6, semantics="textbook", probe_every=1,
    )
    cfg.robustness.mass_tol = 1e-4
    eng = make_engine("cpu", cfg).build(g)
    eng._at = eng._at * 1.15  # corrupt the link weights
    probes = ConvergenceProbes(1, topk=16)
    with pytest.raises(SolverHealthError) as ei:
        eng.run(probes=probes)
    assert "mass ledger names the link term" in str(ei.value)
    assert eng.health.get("mass_leak") == "link"


def test_ledger_detects_dangling_mask_leak():
    """A vertex with no out-edges MISSING from the dangling mask drops
    its mass on the floor every step — the ledger names 'dangling'."""
    g = build_graph(*rmat_edges(9, 16, seed=8), n=1 << 9)
    cfg = PageRankConfig(num_iters=3, semantics="textbook",
                         probe_every=1)
    eng = make_engine("cpu", cfg).build(g)
    # Knock half the dangling vertices out of the mass mask.
    dang = np.flatnonzero(eng._dangling)
    assert dang.size >= 2
    eng._dangling[dang[::2]] = 0.0
    probes = ConvergenceProbes(1, topk=16)
    eng.run(probes=probes)
    assert probes.ledger_violations
    assert all(v["leak"] == "dangling"
               for v in probes.ledger_violations)


def test_probed_ledger_run_matches_plain_run_bitwise():
    """Probe transparency survives the ledger: a probed (ledger-on)
    f32 run's ranks are bit-identical to the unprobed run's."""
    g = build_graph(*rmat_edges(10, 16, seed=9), n=1 << 10)
    cfg = PageRankConfig(num_iters=5, semantics="textbook")
    r_plain = make_engine("jax", cfg).build(g).run()
    eng, _probes = _run_probed("jax", g, iters=5)
    assert np.array_equal(r_plain, eng.ranks())


# -- booby trap (disarmed = zero profile computations) ----------------------


def test_disarmed_build_makes_zero_profile_calls(monkeypatch):
    def boom(*a, **k):
        raise AssertionError("profile computation on a DISARMED build")

    monkeypatch.setattr(graph_profile, "device_stats", boom)
    monkeypatch.setattr(graph_profile, "profile_graph", boom)
    src, dst = rmat_edges(9, 16, seed=1)
    dg = db.build_ell_device(np.asarray(src).copy(),
                             np.asarray(dst).copy(), n=1 << 9)
    assert dg.num_edges > 0
    assert graph_profile.get_profile() is None


def test_armed_build_is_bit_identical_to_disarmed():
    src, dst = rmat_edges(9, 16, seed=2)
    a = db.build_ell_device(np.asarray(src).copy(),
                            np.asarray(dst).copy(), n=1 << 9)
    graph_profile.arm()
    b = db.build_ell_device(np.asarray(src).copy(),
                            np.asarray(dst).copy(), n=1 << 9)
    graph_profile.disarm()
    assert a.fingerprint() == b.fingerprint()
    assert np.array_equal(np.asarray(a.src), np.asarray(b.src))
    assert np.array_equal(np.asarray(a.row_block),
                          np.asarray(b.row_block))
    assert np.array_equal(np.asarray(a.out_degree),
                          np.asarray(b.out_degree))
    # ... and the solves from each are bit-identical too.
    cfg = PageRankConfig(num_iters=3, semantics="textbook")
    ra = make_engine("jax", cfg).build_device(a).run()
    rb = make_engine("jax", cfg).build_device(b).run()
    assert np.array_equal(ra, rb)


# -- skew-driven prediction --------------------------------------------------


@needs_mesh
def test_predicted_skew_within_10pct_of_measured():
    """The ISSUE-13 acceptance bound: predicted per-device straggler
    skew vs the measured per-device edge counts on the 8-fake-device
    mesh, at the smoke geometry (scale 14)."""
    g = build_graph(*rmat_edges(14, 16, seed=1), n=1 << 14)
    cfg = PageRankConfig(num_iters=1, semantics="textbook",
                         vertex_sharded=True, num_devices=8)
    eng = make_engine("jax", cfg).build(g)
    lay = eng.layout_info()
    prof = graph_profile.profile_graph(
        g, group=int(lay.get("group") or 1))
    pred = comms.predict_from_profile(prof, 8)
    meas = comms.measured_device_edges(eng)
    assert meas is not None and int(meas.sum()) == g.num_edges
    mskew = float(meas.max() / meas.mean())
    assert pred["predicted_straggler_skew"] == pytest.approx(
        mskew, rel=0.10)
    # The per-device predicted counts track the measured ones too.
    assert np.allclose(pred["predicted_device_edges"], meas,
                       rtol=0.25, atol=g.num_edges * 0.02)


def test_predict_halo_head_k_shape():
    g = build_graph(*rmat_edges(10, 16, seed=3), n=1 << 10)
    prof = graph_profile.profile_graph(g)
    assert comms.predict_halo_head_k(prof, 1) == 0
    k8 = comms.predict_halo_head_k(prof, 8)
    assert k8 % 128 == 0 and 0 <= k8 <= prof.n_padded
    # A hub-heavy profile (every vertex read by every shard) must
    # choose to replicate a head.
    hub = graph_profile.GraphProfile(
        n=1 << 16, n_padded=1 << 16, num_edges=1 << 22, raw_edges=None,
        self_loops=None, dangling_count=0, zero_in_count=0,
        in_hist=[0] * 10 + [1 << 16] + [0] * (graph_profile.HIST_BINS
                                              - 11),
        out_hist=[0] * graph_profile.HIST_BINS,
        top_hub_ids=[], top_hub_in_degrees=[], partition_edges=[1],
        stripe_span=0)
    assert comms.predict_halo_head_k(hub, 8) > 0


def test_prediction_none_without_block_geometry():
    prof = graph_profile.GraphProfile(
        n=128, n_padded=128, num_edges=10, raw_edges=None,
        self_loops=None, dangling_count=0, zero_in_count=0,
        in_hist=[0] * graph_profile.HIST_BINS,
        out_hist=[0] * graph_profile.HIST_BINS,
        top_hub_ids=[], top_hub_in_degrees=[], partition_edges=[10],
        stripe_span=0)
    assert comms.predict_device_load(prof, 8) is None
    pred = comms.predict_from_profile(prof, 8)
    assert pred["predicted_straggler_skew"] is None


# -- job artifact ------------------------------------------------------------


def test_profile_artifact_round_trip_and_tamper(tmp_path):
    from pagerank_tpu import jobs

    src, dst = rmat_edges(10, 16, seed=0)
    graph_profile.arm()
    db.build_ell_device(np.asarray(src).copy(), np.asarray(dst).copy(),
                        n=1 << 10, stripe_size=256)
    prof = graph_profile.get_profile()
    graph_profile.disarm()

    path = str(tmp_path / "profile.npz")
    arrays, meta = prof.to_arrays()
    jobs.save_artifact(path, arrays, meta)
    arrays2, meta2 = jobs.load_artifact(path)
    back = graph_profile.GraphProfile.from_arrays(arrays2, meta2)
    assert back.summary() == prof.summary()
    assert np.array_equal(back.block_edges, prof.block_edges)
    assert np.array_equal(back.block_rows, prof.block_rows)

    # Tamper 1: modify one payload array, keeping the STORED meta +
    # checksum entries verbatim — the recomputed digest must reject.
    with np.load(path) as z:
        entries = {k: z[k].copy() for k in z.files}
    entries["in_hist"][0] += 1
    with open(path, "wb") as f:
        np.savez(f, **entries)
    with pytest.raises(jobs.ArtifactCorruptError):
        jobs.load_artifact(path)
    # Tamper 2: a truncated file is unreadable, same exception class.
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:-7])
    with pytest.raises(jobs.ArtifactCorruptError):
        jobs.load_artifact(path)


def test_job_supervisor_profile_fingerprint_gate(tmp_path):
    from pagerank_tpu import jobs

    src, dst = rmat_edges(9, 16, seed=0)
    graph_profile.arm()
    db.build_ell_device(np.asarray(src).copy(), np.asarray(dst).copy(),
                        n=1 << 9)
    prof = graph_profile.get_profile()
    graph_profile.disarm()
    job = jobs.JobSupervisor(str(tmp_path / "job"))
    job.save_profile(prof)
    back = job.load_profile(prof.fingerprint)
    assert back is not None and back.summary() == prof.summary()
    # A different graph's fingerprint never restores this profile.
    with pytest.warns(RuntimeWarning):
        assert job.load_profile("dev-ffffffffffff") is None


# -- surfaces: CLI / report / history ---------------------------------------


def test_obs_graph_cli_strict_json(capsys):
    from pagerank_tpu.obs.__main__ import main as obs_main

    rc = obs_main(["graph", "--scale", "9", "--iters", "2", "--json"])
    out = capsys.readouterr().out
    doc = json.loads(out, parse_constant=lambda c: (
        (_ for _ in ()).throw(ValueError(f"non-strict constant {c}"))
    ))
    assert rc == 0
    assert {"profile", "prediction", "measured", "ledger"} <= set(doc)
    assert doc["ledger"]["ok"] is True
    assert doc["ledger"]["entries"] == 2
    assert doc["profile"]["num_edges"] > 0


def test_obs_graph_cli_device_build(capsys):
    from pagerank_tpu.obs.__main__ import main as obs_main

    rc = obs_main(["graph", "--scale", "9", "--iters", "2",
                   "--device-build", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["profile"]["source"] == "device_build"
    assert doc["profile"]["duplicate_edges"] is not None


def test_report_diff_calls_out_data_drift_before_perf():
    from pagerank_tpu.obs import report as report_mod

    g = build_graph(*rmat_edges(9, 16, seed=0), n=1 << 9)
    prof_a = graph_profile.profile_graph(g)
    g2 = build_graph(*rmat_edges(9, 16, seed=12), n=1 << 9)
    prof_b = graph_profile.profile_graph(g2)

    def rep(prof):
        r = report_mod.build_run_report(summary={})
        r["graph"] = {"n": prof.n, "num_edges": prof.num_edges,
                      "profile": prof.summary()}
        r["spans"] = {"solve/step": {"total_s": 1.0, "count": 1,
                                     "mean_s": 1.0}}
        return r

    text = report_mod.diff_reports(rep(prof_a), rep(prof_b))
    assert "data DIFFERS" in text
    assert text.index("data DIFFERS") < text.index("phase wall deltas")
    # Identical data says so instead.
    text2 = report_mod.diff_reports(rep(prof_a), rep(prof_a))
    assert "data: graph profile identical" in text2


def test_report_keys_include_graph():
    from pagerank_tpu.obs import report as report_mod

    rep = report_mod.build_run_report()
    assert set(report_mod.REPORT_KEYS) <= set(rep)
    assert "graph" in rep


def _ledger_rec(i, eps, dangling, skew=None, cost=100.0, env=None):
    legs = {"fast_f32": {
        "edges_per_sec_per_chip": eps,
        "cost_bytes_per_edge": cost,
        "graph_dangling_fraction": dangling,
    }}
    if skew is not None:
        legs["fast_f32"]["graph_partition_skew"] = skew
    return {
        "schema_version": 1, "kind": "bench_single",
        "source": f"r{i}.json", "env": env or {"backend": "cpu",
                                               "device_kind": "cpu",
                                               "jax_version": "0.4.1"},
        "workload": {}, "legs": legs, "extras": {}, "legacy": False,
    }


def test_history_data_change_attribution_and_gate():
    from pagerank_tpu.obs import history as history_mod

    base = [_ledger_rec(i, 100.0 + i * 0.01, 0.25) for i in range(6)]
    # Throughput halves, cost model flat, dangling fraction doubled:
    # a DATA change, not a program regression.
    target = _ledger_rec(9, 50.0, 0.5)
    changes = history_mod.detect_changes(base + [target])
    flagged = [c for c in changes if c.flagged
               and c.metric == "edges_per_sec_per_chip"]
    assert flagged and flagged[0].classification == "data-change"
    assert "data changed shape" in flagged[0].evidence
    gate = history_mod.evaluate_gate(base + [target])
    assert gate.ok  # data drift warns, never fails
    assert any(w.startswith("DATA ") for w in gate.drift_warnings)
    # Same move WITHOUT profile motion still gates as program-change.
    target2 = _ledger_rec(9, 50.0, 0.25)
    gate2 = history_mod.evaluate_gate(base + [target2])
    assert not gate2.ok


def test_history_run_report_carries_graph_leg_metrics():
    from pagerank_tpu.obs import history as history_mod
    from pagerank_tpu.obs import report as report_mod

    g = build_graph(*rmat_edges(9, 16, seed=0), n=1 << 9)
    prof = graph_profile.profile_graph(g, partition_span=128)
    rep = report_mod.build_run_report(
        config={"dtype": "float32"},
        summary={"edges_per_sec_per_chip": 1e6,
                 "mean_iter_seconds": 0.01},
    )
    rep["graph"] = {"n": g.n, "num_edges": g.num_edges,
                    "profile": prof.summary()}
    rep["probes"] = [{"iteration": 0, "topk_concentration": 0.31}]
    rec = history_mod.normalize_result(rep, source="run_report.json")
    leg = rec["legs"]["fast_f32"]
    assert leg["graph_dangling_fraction"] == pytest.approx(
        prof.dangling_fraction)
    assert leg["graph_partition_skew"] == pytest.approx(
        prof.partition_skew())
    assert leg["graph_topk_concentration"] == pytest.approx(0.31)


def test_history_pre_issue13_records_ingest_unchanged():
    """Normalization regression: pre-ISSUE-13 artifacts produce the
    exact records already in the checked-in ledger (same content
    hash), with no graph_* keys invented."""
    from pagerank_tpu.obs import history as history_mod

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ledger = history_mod.read_ledger(
        os.path.join(repo, "PERF_HISTORY.jsonl"))
    assert ledger
    by_source = {r.get("source"): r for r in ledger}
    name = "BENCH_r05.json"
    with open(os.path.join(repo, name)) as f:
        doc = json.load(f)
    rec = history_mod.normalize_result(doc, source=name)
    assert rec["content_hash"] == by_source[name]["content_hash"]
    for leg in rec["legs"].values():
        assert not any(k.startswith("graph_") for k in leg)
