"""Compiler-plane observability (ISSUE 11; pagerank_tpu/obs/hlo.py):
the HLO text parser + gather-strategy classifier on synthetic and real
modules, the harvest-is-lazy booby trap, PTH001-003 contract verdicts,
the as_text degradation regression, and the CLI/schema round-trips."""

import json

import jax
import numpy as np
import pytest

from pagerank_tpu import JaxTpuEngine, PageRankConfig, build_graph, obs
from pagerank_tpu.analysis import contracts as contracts_mod
from pagerank_tpu.obs import hlo as obs_hlo
from pagerank_tpu.utils import jax_compat


@pytest.fixture(autouse=True)
def _clean_ledgers():
    obs.get_registry().reset()
    obs_hlo.reset()
    yield
    obs_hlo.reset()


def _graph(n=512, e=4096, seed=0):
    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


# -- synthetic HLO texts -----------------------------------------------------

NATIVE_TEXT = """\
HloModule synthetic_native, is_scheduled=true

%fused_gather (param_0: f32[131072], param_1: s32[4096]) -> f32[4096] {
  %param_0 = f32[131072]{0} parameter(0)
  %param_1 = s32[4096]{0} parameter(1)
  %bitcast.1 = s32[4096,1]{1,0} bitcast(s32[4096]{0} %param_1)
  ROOT %gather.0 = f32[4096]{0} gather(f32[131072]{0} %param_0, s32[4096,1]{1,0} %bitcast.1), offset_dims={}, collapsed_slice_dims={0}, start_index_map={0}, index_vector_dim=1, slice_sizes={1}
}

ENTRY %main.1 (Arg_0.1: f32[131072], Arg_1.2: s32[4096]) -> f32[4096] {
  %Arg_0.1 = f32[131072]{0} parameter(0)
  %Arg_1.2 = s32[4096]{0} parameter(1)
  %all-reduce.0 = f32[4096]{0} all-reduce(f32[4096]{0} %Arg_0.1), replica_groups={}, to_apply=%add.1
  ROOT %fusion.0 = f32[4096]{0} fusion(f32[131072]{0} %Arg_0.1, s32[4096]{0} %Arg_1.2), kind=kLoop, calls=%fused_gather
}
"""

# The bf16-streamed variant: the gather's table operand chain carries a
# bf16 convert — the mechanical fast_bf16 verification.
BF16_TEXT = NATIVE_TEXT.replace(
    "  %bitcast.1 = s32[4096,1]{1,0} bitcast(s32[4096]{0} %param_1)\n"
    "  ROOT %gather.0 = f32[4096]{0} gather(f32[131072]{0} %param_0,",
    "  %bitcast.1 = s32[4096,1]{1,0} bitcast(s32[4096]{0} %param_1)\n"
    "  %convert.2 = bf16[131072]{0} convert(f32[131072]{0} %param_0)\n"
    "  %convert.1 = f32[131072]{0} convert(bf16[131072]{0} %convert.2)\n"
    "  ROOT %gather.0 = f32[4096]{0} gather(f32[131072]{0} %convert.1,",
)

# The defeated lowering: no native gather — a while loop doing one
# scalar table load + one scalar result update per index (trip bound
# 4096 in the condition).
EXPANDED_TEXT = """\
HloModule synthetic_expanded, is_scheduled=true

%body.1 (p.1: (s32[], f32[4096], s32[4096], f32[131072])) -> (s32[], f32[4096], s32[4096], f32[131072]) {
  %p.1 = (s32[], f32[4096]{0}, s32[4096]{0}, f32[131072]{0}) parameter(0)
  %i.1 = s32[] get-tuple-element((s32[], f32[4096]{0}, s32[4096]{0}, f32[131072]{0}) %p.1), index=0
  %acc.1 = f32[4096]{0} get-tuple-element((s32[], f32[4096]{0}, s32[4096]{0}, f32[131072]{0}) %p.1), index=1
  %idx.1 = s32[4096]{0} get-tuple-element((s32[], f32[4096]{0}, s32[4096]{0}, f32[131072]{0}) %p.1), index=2
  %table.1 = f32[131072]{0} get-tuple-element((s32[], f32[4096]{0}, s32[4096]{0}, f32[131072]{0}) %p.1), index=3
  %ds.idx = s32[1]{0} dynamic-slice(s32[4096]{0} %idx.1, s32[] %i.1), dynamic_slice_sizes={1}
  %bc.1 = s32[] bitcast(s32[1]{0} %ds.idx)
  %ds.val = f32[1]{0} dynamic-slice(f32[131072]{0} %table.1, s32[] %bc.1), dynamic_slice_sizes={1}
  %dus.1 = f32[4096]{0} dynamic-update-slice(f32[4096]{0} %acc.1, f32[1]{0} %ds.val, s32[] %i.1)
  %one.1 = s32[] constant(1)
  %next.1 = s32[] add(s32[] %i.1, s32[] %one.1)
  ROOT %tuple.1 = (s32[], f32[4096]{0}, s32[4096]{0}, f32[131072]{0}) tuple(s32[] %next.1, f32[4096]{0} %dus.1, s32[4096]{0} %idx.1, f32[131072]{0} %table.1)
}

%cond.1 (p.2: (s32[], f32[4096], s32[4096], f32[131072])) -> pred[] {
  %p.2 = (s32[], f32[4096]{0}, s32[4096]{0}, f32[131072]{0}) parameter(0)
  %i.2 = s32[] get-tuple-element((s32[], f32[4096]{0}, s32[4096]{0}, f32[131072]{0}) %p.2), index=0
  %n.1 = s32[] constant(4096)
  ROOT %lt.1 = pred[] compare(s32[] %i.2, s32[] %n.1), direction=LT
}

ENTRY %main.2 (Arg_0.1: f32[131072], Arg_1.2: s32[4096]) -> f32[4096] {
  %Arg_0.1 = f32[131072]{0} parameter(0)
  %Arg_1.2 = s32[4096]{0} parameter(1)
  %zero.1 = s32[] constant(0)
  %init.1 = f32[4096]{0} broadcast(s32[] %zero.1), dimensions={}
  %tuple.0 = (s32[], f32[4096]{0}, s32[4096]{0}, f32[131072]{0}) tuple(s32[] %zero.1, f32[4096]{0} %init.1, s32[4096]{0} %Arg_1.2, f32[131072]{0} %Arg_0.1)
  %while.0 = (s32[], f32[4096]{0}, s32[4096]{0}, f32[131072]{0}) while((s32[], f32[4096]{0}, s32[4096]{0}, f32[131072]{0}) %tuple.0), condition=%cond.1, body=%body.1
  ROOT %gte.1 = f32[4096]{0} get-tuple-element((s32[], f32[4096]{0}, s32[4096]{0}, f32[131072]{0}) %while.0), index=1
}
"""

NO_GATHER_TEXT = """\
HloModule synthetic_none, is_scheduled=true

ENTRY %main.3 (Arg_0.1: f32[4096]) -> f32[4096] {
  %Arg_0.1 = f32[4096]{0} parameter(0)
  ROOT %add.0 = f32[4096]{0} add(f32[4096]{0} %Arg_0.1, f32[4096]{0} %Arg_0.1)
}
"""


# -- classifier on synthetic texts ------------------------------------------


def test_classifier_native_gather():
    rep = obs_hlo.inspect_text("t", NATIVE_TEXT)
    g = rep.gather
    assert g["strategy"] == "native"
    assert g["n_gathers"] == 1 and g["expansion_sites"] == []
    hg = g["hot_gather"]
    assert hg["output_elements"] == 4096
    assert hg["table_dtype"] == "f32" and hg["stream_dtype"] == "f32"
    assert hg["slice_sizes"] == [1]
    assert hg["in_while"] is False
    assert rep.fusion_count == 1 and rep.while_count == 0


def test_classifier_while_expansion():
    rep = obs_hlo.inspect_text("t", EXPANDED_TEXT)
    g = rep.gather
    assert g["strategy"] == "expanded"
    assert g["hot_gather"] is None
    assert g["expansion_sites"] == ["body.1"]
    assert rep.while_count == 1


def test_classifier_no_gather():
    rep = obs_hlo.inspect_text("t", NO_GATHER_TEXT)
    assert rep.gather["strategy"] == "none"
    assert rep.gather["expansion_sites"] == []


def test_classifier_bf16_stream_detected():
    """The fast_bf16 verification: a bf16 convert in the gather's
    table operand chain is reported as the streamed dtype even though
    the gather itself reads/writes f32."""
    rep = obs_hlo.inspect_text("t", BF16_TEXT)
    hg = rep.gather["hot_gather"]
    assert hg["table_dtype"] == "f32"
    assert hg["stream_dtype"] == "bf16"


def test_small_trip_chunk_loop_is_not_expansion():
    """A short-trip while (the engine's chunk scan class) with scalar
    bookkeeping slices must NOT classify as an expansion — the trip
    bound gate."""
    text = EXPANDED_TEXT.replace("constant(4096)", "constant(33)")
    rep = obs_hlo.inspect_text("t", text)
    assert rep.gather["expansion_sites"] == []
    assert rep.gather["strategy"] == "none"


SCATTER_RMW_TEXT = """\
HloModule synthetic_scatter, is_scheduled=true

%body.s (p.1: (s32[], f32[512], s32[4096], f32[4096])) -> (s32[], f32[512], s32[4096], f32[4096]) {
  %p.1 = (s32[], f32[512]{0}, s32[4096]{0}, f32[4096]{0}) parameter(0)
  %i.1 = s32[] get-tuple-element((s32[], f32[512]{0}, s32[4096]{0}, f32[4096]{0}) %p.1), index=0
  %acc.1 = f32[512]{0} get-tuple-element((s32[], f32[512]{0}, s32[4096]{0}, f32[4096]{0}) %p.1), index=1
  %idx.1 = s32[4096]{0} get-tuple-element((s32[], f32[512]{0}, s32[4096]{0}, f32[4096]{0}) %p.1), index=2
  %upd.1 = f32[4096]{0} get-tuple-element((s32[], f32[512]{0}, s32[4096]{0}, f32[4096]{0}) %p.1), index=3
  %ds.idx = s32[1]{0} dynamic-slice(s32[4096]{0} %idx.1, s32[] %i.1), dynamic_slice_sizes={1}
  %bc.1 = s32[] bitcast(s32[1]{0} %ds.idx)
  %ds.upd = f32[1]{0} dynamic-slice(f32[4096]{0} %upd.1, s32[] %i.1), dynamic_slice_sizes={1}
  %ds.old = f32[1]{0} dynamic-slice(f32[512]{0} %acc.1, s32[] %bc.1), dynamic_slice_sizes={1}
  %add.1 = f32[1]{0} add(f32[1]{0} %ds.old, f32[1]{0} %ds.upd)
  %dus.1 = f32[512]{0} dynamic-update-slice(f32[512]{0} %acc.1, f32[1]{0} %add.1, s32[] %bc.1)
  %one.1 = s32[] constant(1)
  %next.1 = s32[] add(s32[] %i.1, s32[] %one.1)
  ROOT %tuple.1 = (s32[], f32[512]{0}, s32[4096]{0}, f32[4096]{0}) tuple(s32[] %next.1, f32[512]{0} %dus.1, s32[4096]{0} %idx.1, f32[4096]{0} %upd.1)
}

%cond.s (p.2: (s32[], f32[512], s32[4096], f32[4096])) -> pred[] {
  %p.2 = (s32[], f32[512]{0}, s32[4096]{0}, f32[4096]{0}) parameter(0)
  %i.2 = s32[] get-tuple-element((s32[], f32[512]{0}, s32[4096]{0}, f32[4096]{0}) %p.2), index=0
  %n.1 = s32[] constant(4096)
  ROOT %lt.1 = pred[] compare(s32[] %i.2, s32[] %n.1), direction=LT
}

ENTRY %main.4 (Arg_0.1: f32[4096], Arg_1.2: s32[4096]) -> f32[512] {
  %Arg_0.1 = f32[4096]{0} parameter(0)
  %Arg_1.2 = s32[4096]{0} parameter(1)
  %zero.1 = s32[] constant(0)
  %init.1 = f32[512]{0} broadcast(s32[] %zero.1), dimensions={}
  %tuple.0 = (s32[], f32[512]{0}, s32[4096]{0}, f32[4096]{0}) tuple(s32[] %zero.1, f32[512]{0} %init.1, s32[4096]{0} %Arg_1.2, f32[4096]{0} %Arg_0.1)
  %while.0 = (s32[], f32[512]{0}, s32[4096]{0}, f32[4096]{0}) while((s32[], f32[512]{0}, s32[4096]{0}, f32[4096]{0}) %tuple.0), condition=%cond.s, body=%body.s
  ROOT %gte.1 = f32[512]{0} get-tuple-element((s32[], f32[512]{0}, s32[4096]{0}, f32[4096]{0}) %while.0), index=1
}
"""


def test_scalarized_scatter_is_not_gather_expansion():
    """The scatter-vs-gather discriminator (the coo regression): a
    scalarized SCATTER loop read-modify-writes its target — the dus
    destination is also a scalar dynamic-slice source — while a
    defeated gather's output is write-only. CPU XLA expands scatter-add
    this way for coo's merge; it must not classify as the
    fast-gather-defeated signature."""
    rep = obs_hlo.inspect_text("t", SCATTER_RMW_TEXT)
    assert rep.gather["expansion_sites"] == []
    assert rep.gather["strategy"] == "none"


def test_collective_multiset_with_operand_bytes():
    rep = obs_hlo.inspect_text("t", NATIVE_TEXT)
    assert rep.collectives == [
        {"op": "all-reduce", "operand_bytes": 4096 * 4, "dtype": "f32"}
    ]


def test_fingerprint_moves_with_lowering_not_with_form_name():
    a = obs_hlo.inspect_text("a", NATIVE_TEXT)
    b = obs_hlo.inspect_text("b", NATIVE_TEXT)
    c = obs_hlo.inspect_text("c", EXPANDED_TEXT)
    assert a.fingerprint == b.fingerprint
    assert a.fingerprint != c.fingerprint


def test_report_is_strict_json():
    rep = obs_hlo.inspect_text("t", NATIVE_TEXT, num_edges=4096)
    doc = json.loads(json.dumps(rep.to_json(), allow_nan=False))
    assert doc["fingerprint"] == rep.fingerprint
    assert doc["hlo_bytes_per_edge"] > 0
    assert "text" not in doc


# -- real compiled programs --------------------------------------------------


def test_inspect_compiled_real_gather():
    compiled = jax.jit(lambda t, i: t[i]).lower(
        jax.ShapeDtypeStruct((1024,), np.float32),
        jax.ShapeDtypeStruct((256,), np.int32),
    ).compile()
    rep = obs_hlo.inspect_compiled("probe", compiled, num_edges=256,
                                   record=False)
    assert rep is not None
    assert rep.gather["strategy"] == "native"
    assert rep.hlo_bytes_per_edge > 0
    # Same program -> same structural fingerprint.
    rep2 = obs_hlo.inspect_compiled("probe", compiled, record=False)
    assert rep2.fingerprint == rep.fingerprint


def test_engine_lowering_reports_and_gauge():
    eng = JaxTpuEngine(PageRankConfig(num_iters=2)).build(_graph())
    snap = eng.lowering_reports()
    assert "step" in snap
    assert snap["step"]["gather"]["strategy"] == "native"
    # Harvest disarms itself after the pass and publishes the
    # reconciliation gauge.
    assert not obs_hlo.armed()
    gauges = obs.get_registry().snapshot()["gauges"]
    assert gauges["cost.step.hlo_bytes_per_edge"] > 0
    # Repeat calls are ledger hits (no recompile, same snapshot).
    assert eng.lowering_reports() == snap


def test_bf16_stream_verified_on_partitioned_bf16_engine():
    eng = JaxTpuEngine(PageRankConfig(
        num_iters=2, partition_span=256, stream_dtype="bfloat16",
    )).build(_graph())
    snap = eng.lowering_reports()
    hg = snap["step"]["gather"]["hot_gather"]
    assert hg["stream_dtype"] == "bf16"
    # The plain partitioned form streams f32 — the two fingerprints
    # must differ (the bf16 bet is visible in the lowering).
    eng2 = JaxTpuEngine(PageRankConfig(
        num_iters=2, partition_span=256,
    )).build(_graph())
    obs_hlo.reset()
    snap2 = eng2.lowering_reports()
    assert snap2["step"]["gather"]["hot_gather"]["stream_dtype"] == "f32"
    assert snap2["step"]["fingerprint"] != snap["step"]["fingerprint"]


def test_lowering_reports_not_stale_across_engines_or_rebuilds():
    """The per-engine memo regression: the process-global hlo ledger is
    shared, so a SECOND engine (or an in-place rebuild) must never be
    handed the first program's verdict — each build re-classifies."""
    g = _graph()
    a = JaxTpuEngine(PageRankConfig(num_iters=2)).build(g)
    fp_a = a.lowering_reports()["step"]["fingerprint"]
    # No obs_hlo.reset() in between — the exact staleness scenario.
    b = JaxTpuEngine(PageRankConfig(num_iters=2,
                                    partition_span=256)).build(g)
    fp_b = b.lowering_reports()["step"]["fingerprint"]
    assert fp_b != fp_a
    # And an in-place rebuild on a NEW graph drops the cache too.
    b.build(_graph(n=1024, e=8192, seed=7))
    fp_b2 = b.lowering_reports()["step"]["fingerprint"]
    assert fp_b2 != fp_b


# -- harvest-is-lazy booby trap ---------------------------------------------


def test_disarmed_run_makes_zero_inspector_calls(monkeypatch):
    """The acceptance criterion: with the inspector disarmed (the
    default), a full build + solve + cost harvest makes ZERO inspector
    calls — every entry point is booby-trapped (the tracer/sampler
    discipline applied to the compiler plane)."""

    def boom(*a, **k):
        raise AssertionError("hlo inspector touched on a plain run")

    monkeypatch.setattr(obs_hlo, "inspect_compiled", boom)
    monkeypatch.setattr(obs_hlo, "inspect_text", boom)
    monkeypatch.setattr(obs_hlo, "parse_hlo_text", boom)
    g = _graph(seed=1)
    eng = JaxTpuEngine(PageRankConfig(num_iters=3)).build(g)
    eng.run_fast()          # stepwise dispatch path
    eng.run_fused(1)        # the fused compile point (maybe_inspect)
    eng.cost_reports()      # the cost harvest compile point
    assert obs_hlo.ledger_snapshot() == {}


def test_disarmed_device_build_makes_zero_inspector_calls(monkeypatch):
    """stage_call (utils/compile_cache) is a harvest point too — a
    disarmed device build must never reach the inspector."""
    import jax.numpy as jnp

    from pagerank_tpu.ops import device_build as db
    from pagerank_tpu.utils import compile_cache

    def boom(*a, **k):
        raise AssertionError("hlo inspector touched during a build")

    monkeypatch.setattr(obs_hlo, "inspect_compiled", boom)
    monkeypatch.setattr(obs_hlo, "inspect_text", boom)
    compile_cache.clear_stage_cache()
    rng = np.random.default_rng(2)
    src = jnp.asarray(rng.integers(0, 256, 2048), jnp.int32)
    dst = jnp.asarray(rng.integers(0, 256, 2048), jnp.int32)
    dg = db.build_ell_device(src, dst, n=256, with_weights=False)
    assert dg.num_edges > 0
    assert obs_hlo.ledger_snapshot() == {}


def test_armed_stage_call_harvests_build_forms():
    import jax.numpy as jnp

    from pagerank_tpu.ops import device_build as db
    from pagerank_tpu.utils import compile_cache

    compile_cache.clear_stage_cache()
    obs_hlo.arm()
    try:
        rng = np.random.default_rng(3)
        src = jnp.asarray(rng.integers(0, 256, 2048), jnp.int32)
        dst = jnp.asarray(rng.integers(0, 256, 2048), jnp.int32)
        db.build_ell_device(src, dst, n=256, with_weights=False)
    finally:
        obs_hlo.disarm()
    snap = obs_hlo.ledger_snapshot()
    assert any(form.startswith("build/") for form in snap)


# -- degradation: backends without HLO text ---------------------------------


def test_inspect_compiled_tolerates_raising_as_text():
    """The ISSUE-11 satellite: a Compiled whose as_text raises (or
    returns empty) degrades to a logged None — never an exception."""

    class Broken:
        def as_text(self):
            raise NotImplementedError("bare PJRT plugin")

        def hlo_modules(self):
            raise NotImplementedError

    assert jax_compat.compiled_hlo_text(Broken()) is None
    assert obs_hlo.inspect_compiled("t", Broken()) is None

    class Empty:
        def as_text(self):
            return ""

        def hlo_modules(self):
            return []

    assert jax_compat.compiled_hlo_text(Empty()) is None
    assert obs_hlo.inspect_compiled("t", Empty()) is None


def test_pth_contracts_unknown_verdict_nonblocking(monkeypatch):
    """PTH on a backend that hides its HLO: a surfaced-but-non-blocking
    unknown — zero findings, mirroring the fit check's memory_analysis
    degradation."""
    monkeypatch.setattr(jax_compat, "compiled_hlo_text",
                        lambda compiled: None)
    form = next(f for f in contracts_mod.engine_forms(1)
                if f.name == "ell")
    eng = form.build()
    findings = contracts_mod.check_hlo_form(eng, form)
    assert findings == [], [f.render() for f in findings]


def test_step_key_stability_tolerates_raising_as_text(monkeypatch):
    """analysis/contracts.check_step_key_stability (the PTC004 text
    diff) must also degrade to a non-blocking unknown when as_text
    raises — the regression the ISSUE pins."""
    lowered_cls = type(jax.jit(lambda x: x + 1).lower(1.0))

    def boom(self, *a, **k):
        raise NotImplementedError("no text on this backend")

    monkeypatch.setattr(lowered_cls, "as_text", boom)
    findings = contracts_mod.check_step_key_stability(1)
    assert [f for f in findings if f.rule == "PTC004"] == [], \
        [f.render() for f in findings]


# -- PTH verdicts ------------------------------------------------------------


@pytest.mark.parametrize("name", ["ell", "partitioned_bf16", "coo"])
def test_pth_clean_on_real_forms(name):
    form = next(f for f in contracts_mod.engine_forms(1)
                if f.name == name)
    eng = form.build()
    findings = contracts_mod.check_hlo_form(eng, form)
    assert findings == [], [f.render() for f in findings]


def test_pth_catches_expanded_lowering(monkeypatch):
    """Seed the defect PTH001/003 exist for: a step whose optimized
    HLO is the while-loop scalar expansion must fail the contract."""
    monkeypatch.setattr(jax_compat, "compiled_hlo_text",
                        lambda compiled: EXPANDED_TEXT)
    form = next(f for f in contracts_mod.engine_forms(1)
                if f.name == "ell")
    eng = form.build()
    findings = contracts_mod.check_hlo_form(eng, form)
    rules = {f.rule for f in findings}
    assert "PTH001" in rules, [f.render() for f in findings]


def test_pth_fusion_budget(monkeypatch):
    """PTH002: a fusion-count blow-up past the budget is a finding even
    when the gather survives."""
    blown = NATIVE_TEXT + "".join(
        f"""
%fused_pad.{i} (param_0: f32[4096]) -> f32[4096] {{
  %param_0 = f32[4096]{{0}} parameter(0)
  ROOT %fusion.{i + 10} = f32[4096]{{0}} fusion(f32[4096]{{0}} %param_0), kind=kLoop, calls=%fused_gather
}}
"""
        for i in range(contracts_mod.PTH_FUSION_BUDGET + 1)
    )
    monkeypatch.setattr(jax_compat, "compiled_hlo_text",
                        lambda compiled: blown)
    form = next(f for f in contracts_mod.engine_forms(1)
                if f.name == "ell")
    eng = form.build()
    findings = contracts_mod.check_hlo_form(eng, form)
    assert "PTH002" in {f.rule for f in findings}, \
        [f.render() for f in findings]


def test_pth_partial_defeat_flagged(monkeypatch):
    """PTH003: an expansion site NEXT TO a surviving native gather (a
    partially-scalarized program) is still a finding."""
    combined = EXPANDED_TEXT.replace(
        "HloModule synthetic_expanded", "HloModule synthetic_partial"
    ).replace(
        "ENTRY %main.2", "%not_entry.2"
    ) + "\n" + "\n".join(
        line for line in NATIVE_TEXT.splitlines()
        if not line.startswith("HloModule")
    )
    monkeypatch.setattr(jax_compat, "compiled_hlo_text",
                        lambda compiled: combined)
    form = next(f for f in contracts_mod.engine_forms(1)
                if f.name == "ell")
    eng = form.build()
    findings = contracts_mod.check_hlo_form(eng, form)
    assert "PTH003" in {f.rule for f in findings}, \
        [f.render() for f in findings]


def test_pth_rules_listed_in_catalogue(capsys):
    from pagerank_tpu.analysis.__main__ import main as analysis_main

    assert analysis_main(["--list-rules"]) == 0
    text = capsys.readouterr().out
    for rid in ("PTH001", "PTH002", "PTH003"):
        assert rid in text


# -- CLI + schema round-trips ------------------------------------------------


def test_obs_hlo_cli_json_round_trip(capsys):
    from pagerank_tpu.obs.__main__ import main as obs_main

    rc = obs_main(["hlo", "--form", "default,partitioned", "--scale",
                   "10", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out, parse_constant=lambda c: pytest.fail(
        f"non-strict JSON constant {c}"))
    assert set(doc) == {"default", "partitioned"}
    for form, snapshot in doc.items():
        assert "step" in snapshot, (form, sorted(snapshot))
        assert snapshot["step"]["gather"]["strategy"] == "native"
        assert snapshot["step"]["fingerprint"]


def test_obs_hlo_cli_human_and_dump(tmp_path, capsys):
    from pagerank_tpu.obs.__main__ import main as obs_main

    dump = str(tmp_path / "hlo")
    rc = obs_main(["hlo", "--form", "default", "--scale", "10",
                   "--dump-hlo", dump])
    out = capsys.readouterr().out
    assert rc == 0
    assert "gather NATIVE" in out
    files = list((tmp_path / "hlo").iterdir())
    assert files and files[0].suffix == ".hlo"
    assert "HloModule" in files[0].read_text()


def test_obs_hlo_cli_unknown_form():
    from pagerank_tpu.obs.__main__ import main as obs_main

    assert obs_main(["hlo", "--form", "nope"]) == 2
    # A typo'd form must fail fast even next to valid ones (validated
    # BEFORE any graph build), and an empty list is a usage error.
    assert obs_main(["hlo", "--form", "default,partioned"]) == 2
    assert obs_main(["hlo", "--form", ","]) == 2


def test_obs_hlo_cli_alias_forms_both_emitted(capsys):
    """`--form ell,default` must emit BOTH requested keys (one shared
    snapshot — aliases build the same program once), never silently
    drop a name the user asked for."""
    from pagerank_tpu.obs.__main__ import main as obs_main

    rc = obs_main(["hlo", "--form", "ell,default", "--scale", "10",
                   "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert set(doc) == {"ell", "default"}
    assert (doc["ell"]["step"]["fingerprint"]
            == doc["default"]["step"]["fingerprint"])


def test_run_report_carries_lowering_section():
    eng = JaxTpuEngine(PageRankConfig(num_iters=2)).build(_graph())
    eng.lowering_reports()
    report = obs.build_run_report()
    assert "lowering" in report
    assert report["lowering"]["step"]["gather"]["strategy"] == "native"
    json.dumps(report["lowering"], allow_nan=False)


def test_report_diff_renders_lowering_deltas():
    a = obs.build_run_report()
    b = json.loads(json.dumps(a))
    a["lowering"] = {"step": {
        "gather": {"strategy": "native",
                   "hot_gather": {"stream_dtype": "f32"}},
        "fusion_count": 9, "fingerprint": "aaaa",
    }}
    b["lowering"] = {"step": {
        "gather": {"strategy": "expanded", "hot_gather": None},
        "fusion_count": 240, "fingerprint": "bbbb",
    }}
    out = obs.diff_reports(a, b)
    assert "lowering deltas" in out
    assert "gather native -> expanded" in out
    assert "fusions 9 -> 240" in out
    # Identical lowering says so explicitly.
    out2 = obs.diff_reports(b, json.loads(json.dumps(b)))
    assert "lowering: identical" in out2


# -- history: the lowering fingerprint --------------------------------------


def _bench_record(fp, strategy="native", value=3.0e8, bpe=160.0,
                  jaxv="0.4.37"):
    return {
        "metric": "edges_per_sec_per_chip", "value": value,
        "unit": "edges/s/chip", "vs_baseline": 1.0, "build_s": 2.0,
        "costs": {"step": {"bytes_per_edge": bpe,
                           "seconds_per_iter": 0.1}},
        "lowering": {"step": {
            "gather": {"strategy": strategy, "hot_gather": None},
            "fusion_count": 9, "fingerprint": fp,
            "hlo_bytes_per_edge": 170.0,
        }},
        "layout": {"form": "step"},
        "scale": 20, "iters": 50, "edge_factor": 16,
        "schema_version": 2,
        "env": {"backend": "tpu", "device_kind": "TPU v5e",
                "jax_version": jaxv, "git_rev": "abc1234"},
    }


def test_lowering_fingerprint_normalizes_into_leg():
    from pagerank_tpu.obs import history as history_mod

    rec = history_mod.normalize_result(_bench_record("deadbeef0123"),
                                       source="BENCH_r11.json")
    leg = rec["legs"]["fast_f32"]
    assert leg["lowering_fingerprint"] == "deadbeef0123"
    assert leg["gather_strategy"] == "native"
    assert leg["hlo_bytes_per_edge"] == 170.0


def test_pre_issue11_records_ingest_unchanged():
    """Back-compat: artifacts without a lowering block normalize with
    no lowering keys — the checked-in ledger needs no re-ingest."""
    from pagerank_tpu.obs import history as history_mod

    doc = _bench_record("x")
    del doc["lowering"]
    rec = history_mod.normalize_result(doc, source="BENCH_r05.json")
    leg = rec["legs"]["fast_f32"]
    assert "lowering_fingerprint" not in leg
    assert "hlo_bytes_per_edge" not in leg


def test_fingerprint_change_classified_program_change():
    """A rate drop whose baseline cost model is flat but whose
    lowering fingerprint moved (the jax/libtpu-upgrade scenario) must
    gate as program-change, not drift/noise."""
    from pagerank_tpu.obs import history as history_mod

    records = [
        history_mod.normalize_result(_bench_record("aaaa11112222"),
                                     source=f"BENCH_r{i:02d}.json")
        for i in range(1, 5)
    ]
    # Same env, same cost model, HALF the rate, new fingerprint.
    slow = history_mod.normalize_result(
        _bench_record("bbbb33334444", value=1.5e8),
        source="BENCH_r05.json")
    changes = history_mod.detect_changes(records + [slow])
    flagged = [c for c in changes if c.flagged
               and c.metric == "edges_per_sec_per_chip"]
    assert flagged, changes
    assert flagged[0].classification == "program-change"
    assert "lowering fingerprint moved" in flagged[0].evidence
    gate = history_mod.evaluate_gate(records + [slow])
    assert not gate.ok


def test_trend_renders_lowering_fingerprints(capsys):
    from pagerank_tpu.obs import history as history_mod

    records = [
        history_mod.normalize_result(_bench_record("aaaa11112222"),
                                     source="BENCH_r01.json"),
        history_mod.normalize_result(_bench_record("bbbb33334444"),
                                     source="BENCH_r02.json"),
    ]
    out = history_mod.render_trend(records)
    assert "lowering fingerprints" in out
    assert "aaaa1111" in out and "bbbb3333" in out
    assert "LOWERING CHANGED" in out
    # A stable series renders without the change flag.
    out2 = history_mod.render_trend(records[:1])
    assert "LOWERING CHANGED" not in out2
