"""Native C++ ingestion library vs numpy reference paths."""

import numpy as np
import pytest

from pagerank_tpu import build_graph
from pagerank_tpu.ingest import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


def test_parse_matches_python(tmp_path):
    rng = np.random.default_rng(0)
    src, dst = rng.integers(0, 1000, 5000), rng.integers(0, 1000, 5000)
    p = tmp_path / "edges.txt"
    lines = ["# header comment"]
    for i, (s, d) in enumerate(zip(src, dst)):
        lines.append(f"{s}\t{d}" if i % 2 else f"{s} {d}")
        if i % 97 == 0:
            lines.append("# interior comment")
    p.write_text("\n".join(lines) + "\n")
    ns, nd = native.parse_edgelist_native(str(p))
    np.testing.assert_array_equal(ns, src)
    np.testing.assert_array_equal(nd, dst)


def test_parse_missing_file():
    with pytest.raises(FileNotFoundError):
        native.parse_edgelist_native("/nonexistent/file.txt")


def test_parse_odd_tokens(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("0 1\n2\n")
    with pytest.raises(ValueError):
        native.parse_edgelist_native(str(p))


def test_parse_non_integer_token_raises(tmp_path):
    # Regression: tokens with no digits used to spin the parser forever
    # (the digit loop never advanced past e.g. 'x').
    p = tmp_path / "bad_tok.txt"
    p.write_text("0 1\nx y\n")
    with pytest.raises(ValueError):
        native.parse_edgelist_native(str(p))


def test_parse_empty(tmp_path):
    p = tmp_path / "empty.txt"
    p.write_text("# nothing\n")
    s, d = native.parse_edgelist_native(str(p))
    assert len(s) == 0 and len(d) == 0


def test_sort_dedup_matches_numpy():
    rng = np.random.default_rng(1)
    n, e = 500, 20000  # heavy duplicates
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    out = native.sort_dedup_degrees_native(src, dst, n)
    assert out is not None
    ns, nd, odeg, ideg = out
    key = np.unique(dst * np.int64(n) + src)
    np.testing.assert_array_equal(nd, (key // n).astype(np.int32))
    np.testing.assert_array_equal(ns, (key % n).astype(np.int32))
    np.testing.assert_array_equal(odeg, np.bincount(ns, minlength=n))
    np.testing.assert_array_equal(ideg, np.bincount(nd, minlength=n))


def test_build_graph_native_path_equals_numpy():
    # >= 1<<20 edges triggers the native path inside build_graph.
    rng = np.random.default_rng(2)
    n, e = 5000, 1 << 20
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    g_native = build_graph(src, dst, n=n)

    key = np.unique(dst * np.int64(n) + src)
    np.testing.assert_array_equal(g_native.dst, (key // n).astype(np.int32))
    np.testing.assert_array_equal(g_native.src, (key % n).astype(np.int32))
