"""Device-plane observability tests (ISSUE 10; docs/OBSERVABILITY.md
"Device plane"): the structured per-device sampler (typed
device_stats, gauge/watermark/trace fan-out, CPU None-degradation),
comms-vs-compute attribution (schema, state restoration, gauges), the
OOM-preflight fit check (verdicts, exit codes, estimate soundness),
and the off-by-default transparency booby trap."""

import json

import numpy as np
import pytest

import jax

from pagerank_tpu import PageRankConfig, build_graph, make_engine, obs
from pagerank_tpu.engines.jax_engine import JaxTpuEngine
from pagerank_tpu.obs import costs as obs_costs
from pagerank_tpu.obs import devices as obs_devices
from pagerank_tpu.obs import live as obs_live
from pagerank_tpu.obs import trace as obs_trace
from pagerank_tpu.parallel import mesh as mesh_lib


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Process-global tracer/registry/sampler must never leak between
    tests (the obs-test discipline)."""
    obs.disable_tracing()
    obs.get_registry().reset()
    obs_costs.reset()
    obs.disarm_sampler()
    yield
    obs.disable_tracing()
    obs.get_registry().reset()
    obs_costs.reset()
    obs.disarm_sampler()


class _FakeDevice:
    """A stub device whose memory_stats reports like a TPU PJRT client
    — the CPU test substrate reports nothing, so the value-carrying
    paths need a fake."""

    def __init__(self, id=0, stats=None, kind="TPU v99 fake",
                 platform="tpu"):
        self.id = id
        self.platform = platform
        self.device_kind = kind
        self.process_index = 0
        self._stats = stats

    def memory_stats(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


def _graph(n=400, e=3200, seed=0):
    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


# -- structured device stats + the device_view refactor ---------------------


def test_device_stats_typed_and_none_tolerant():
    """CPU devices report no memory stats: every memory field is None,
    identity fields are real (the None-tolerance contract)."""
    stats = mesh_lib.device_stats()
    assert len(stats) == len(jax.devices())
    for s, d in zip(stats, jax.devices()):
        assert s.id == d.id and s.platform == d.platform
        assert s.kind == d.device_kind
        assert s.bytes_in_use is None and s.bytes_limit is None
        assert s.peak_bytes_in_use is None
        assert json.dumps(s.to_json())  # strict-JSON-able


def test_device_stats_reads_memory_fields():
    fake = _FakeDevice(id=3, stats={"bytes_in_use": 7 << 20,
                                    "bytes_limit": 16 << 30,
                                    "peak_bytes_in_use": 9 << 20})
    (s,) = mesh_lib.device_stats([fake])
    assert s.bytes_in_use == 7 << 20
    assert s.bytes_limit == 16 << 30
    assert s.peak_bytes_in_use == 9 << 20


def test_device_stats_survives_raising_memory_stats():
    fake = _FakeDevice(id=1, stats=RuntimeError("plugin gone"))
    (s,) = mesh_lib.device_stats([fake])
    assert s.id == 1 and s.bytes_in_use is None


def test_device_view_renders_from_device_stats():
    """The ISSUE-10 refactor pin: device_view's string output is
    byte-identical to the historical hand-rolled formatting across
    every branch — no stats, use-only, and use+limit."""
    def legacy(d):
        line = f"{d.platform}:{d.id} ({d.device_kind}, " \
               f"proc {d.process_index})"
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            used = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit")
            if used is not None:
                line += f" hbm {used / 1e9:.2f}GB"
                if limit:
                    line += f"/{limit / 1e9:.2f}GB"
        return line

    fakes = [
        _FakeDevice(id=0, stats=None),
        _FakeDevice(id=1, stats=RuntimeError("x")),
        _FakeDevice(id=2, stats={"bytes_in_use": 1234567890}),
        _FakeDevice(id=3, stats={"bytes_in_use": 8 << 30,
                                 "bytes_limit": 16 << 30}),
        _FakeDevice(id=4, stats={"bytes_limit": 16 << 30}),
    ]
    assert list(mesh_lib.device_view(fakes)) == [legacy(f) for f in fakes]
    # And the real backend's rendering (CPU: identity-only lines).
    assert list(mesh_lib.device_view()) == [
        legacy(d) for d in jax.devices()
    ]


# -- the sampler ------------------------------------------------------------


def test_sampler_gauges_watermark_and_cpu_degradation():
    """On value-reporting devices the sampler publishes device.<id>.*
    gauges and keeps the high-water mark across samples (folding the
    backend's own peak counter); on CPU the gauge NAMES register but
    stay unset — and the exporter output still strict-parses (the
    satellite's degradation contract)."""
    from test_telemetry import assert_prometheus_syntax

    fake = _FakeDevice(id=5, stats={"bytes_in_use": 100,
                                    "bytes_limit": 1000})
    sampler = obs_devices.DeviceSampler(devices=[fake])
    sampler.sample()
    fake._stats = {"bytes_in_use": 700, "bytes_limit": 1000,
                   "peak_bytes_in_use": 900}
    sampler.sample()
    fake._stats = {"bytes_in_use": 50, "bytes_limit": 1000}
    sampler.sample()
    g = obs.get_registry().snapshot()["gauges"]
    assert g["device.5.bytes_in_use"] == 50
    assert g["device.5.bytes_limit"] == 1000
    assert g["device.5.peak_bytes"] == 900  # backend peak folded in
    assert g["device.hbm_high_water_bytes"] == 900
    wm = sampler.watermark()
    assert wm["samples"] == 3
    assert wm["hbm_high_water_bytes"] == 900
    assert wm["per_device_peak_bytes"] == {"5": 900}
    assert wm["last"][0]["bytes_in_use"] == 50
    assert_prometheus_syntax(obs_live.render_prometheus())

    # CPU degradation: names registered, values unset, still parseable.
    obs.get_registry().reset()
    cpu_sampler = obs_devices.DeviceSampler()
    cpu_sampler.sample()
    snap = obs.get_registry().snapshot()["gauges"]
    assert "device.0.bytes_in_use" in snap
    assert snap["device.0.bytes_in_use"] is None
    assert cpu_sampler.watermark()["hbm_high_water_bytes"] is None
    assert_prometheus_syntax(obs_live.render_prometheus())


def test_sampler_cadence_via_engine_run():
    """An armed sampler is fed by engine.run at its cadence (the
    watchdog-hook discipline); disarmed runs feed nothing."""
    calls = []

    class CountingSampler(obs_devices.DeviceSampler):
        def sample(self, iteration=None):
            calls.append(iteration)
            return []

    obs_devices.arm_sampler(CountingSampler(every=2))
    calls.clear()  # drop the arm-time baseline sample
    eng = make_engine("cpu", PageRankConfig(num_iters=6)).build(_graph())
    eng.run()
    assert calls == [0, 2, 4]


def test_sampler_chrome_trace_track_schema(tmp_path):
    """Per-device Chrome-trace tracks (the satellite's schema pin):
    each sampled device gets counter events (ph "C") on its OWN pid
    lane plus one process_name metadata event naming it; values are
    the sampled byte fields. A no-value (CPU) device emits no counter
    noise."""
    tr = obs.enable_tracing()
    fakes = [
        _FakeDevice(id=0, stats={"bytes_in_use": 10, "bytes_limit": 99}),
        _FakeDevice(id=1, stats=None),  # CPU-like: silent
    ]
    sampler = obs_devices.DeviceSampler(devices=fakes)
    sampler.sample()
    sampler.sample()
    events = tr.chrome_events()
    counters = [e for e in events if e["ph"] == "C"]
    assert len(counters) == 2  # two samples x one value-reporting dev
    for e in counters:
        assert e["name"] == "device.0.hbm"
        assert e["pid"] == obs_devices.TRACK_PID_BASE + 0
        assert e["args"] == {"bytes_in_use": 10, "bytes_limit": 99}
        assert isinstance(e["ts"], float)
    metas = [e for e in events if e["ph"] == "M"]
    assert len(metas) == 1
    assert metas[0]["pid"] == obs_devices.TRACK_PID_BASE + 0
    assert "tpu:0" in metas[0]["args"]["name"]
    # The JSONL export carries the counters as strict-JSON lines.
    path = str(tmp_path / "t.jsonl")
    tr.export(path)
    kinds = {json.loads(l)["type"] for l in open(path)}
    assert "counter" in kinds


def test_report_section_present_without_armed_sampler():
    """Run reports carry the devices section even with no sampler
    armed (one-shot boundary sample) — the failure-marked-report OOM
    evidence must not depend on an opt-in flag."""
    sec = obs_devices.report_section()
    assert sec["samples"] == 1
    assert len(sec["last"]) == len(jax.devices())
    report = obs.build_run_report()
    assert report["devices"]["samples"] >= 1


# -- transparency booby trap ------------------------------------------------


def test_sampler_and_attribution_off_zero_hot_loop_calls(monkeypatch):
    """With no sampler armed and no attribution requested, a full
    solve makes ZERO sampler/attribution calls (the tracer booby-trap
    discipline applied to the device plane): every entry point is
    trapped, and the exchange-only program is never even compiled."""

    def boom(*a, **k):
        raise AssertionError(
            "device-plane machinery touched on a plain solve")

    monkeypatch.setattr(obs_devices.DeviceSampler, "sample", boom)
    monkeypatch.setattr(obs_devices.DeviceSampler, "on_step", boom)
    monkeypatch.setattr(obs_devices, "attribute_exchange", boom)
    monkeypatch.setattr(JaxTpuEngine, "_exchange_step", boom)
    monkeypatch.setattr(JaxTpuEngine, "time_exchange_split", boom)
    g = _graph(seed=3)
    eng = make_engine("jax", PageRankConfig(
        num_iters=3, num_devices=min(2, len(jax.devices())),
        vertex_sharded=True)).build(g)
    r = eng.run()
    assert np.all(np.isfinite(r))
    # Lazy-compile contract: the exchange program was never lowered.
    assert eng._exchange_fn is None


# -- comms-vs-compute attribution -------------------------------------------


@pytest.mark.parametrize("halo", [False, True])
def test_attribution_schema_and_state_restoration(halo):
    ndev = min(4, len(jax.devices()))
    if ndev < 2:
        pytest.skip("needs a multi-device mesh")
    g = _graph(n=512, e=4096, seed=7)
    cfg = PageRankConfig(num_iters=4, dtype="float32",
                         accum_dtype="float32", num_devices=ndev,
                         vertex_sharded=True, halo_exchange=halo)
    eng = JaxTpuEngine(cfg).build(g)
    # Attribution mid-run must not perturb the solve: a run with an
    # attribution probe in the middle is bit-identical to one without.
    eng2 = JaxTpuEngine(cfg).build(g)
    r_clean = eng2.run_fast()
    eng.run_fast(2)
    att = obs_devices.attribute_exchange(eng, iters=3, warmup=1)
    r_probed = eng.run_fast()
    np.testing.assert_array_equal(r_clean, r_probed)
    assert eng.iteration == 4

    assert att["mode"] == ("sparse" if halo else "dense")
    assert att["exchange_s"] > 0 and att["step_s"] > 0
    assert att["compute_s"] >= 0
    assert 0 <= att["exchange_fraction"] <= 1
    assert att["model_bytes_per_iter"] > 0
    assert att["achieved_bytes_per_sec"] > 0
    gauges = obs.get_registry().snapshot()["gauges"]
    assert gauges["comms.exchange_fraction"] == att["exchange_fraction"]
    assert gauges["comms.achieved_bytes_per_sec"] == \
        att["achieved_bytes_per_sec"]


def test_attribution_none_on_replicated_layout():
    eng = make_engine("jax", PageRankConfig(num_iters=2)).build(_graph())
    assert not eng.has_exchange_program()
    assert obs_devices.attribute_exchange(eng) is None


# -- OOM-preflight fit check ------------------------------------------------


def test_fit_check_passes_at_small_scale():
    res = obs_devices.fit_check(14)
    assert res.fits
    stages = {s.stage for s in res.stages}
    assert {"build/gen", "build/sort", "build/slots", "build/scatter",
            "solve/step"} <= stages
    # Build stages are XLA-harvested at the target shapes, the solve
    # stage is the documented analytic model.
    by_name = {s.stage: s for s in res.stages}
    assert by_name["build/sort"].source == "xla"
    assert by_name["build/sort"].bytes > 0
    assert by_name["solve/step"].source == "model"
    rendered = obs_devices.render_fit(res)
    assert "FITS" in rendered and "build/sort" in rendered


def test_fit_check_fails_at_impossible_scale():
    """A geometry that provably exceeds per-chip HBM (the acceptance
    criterion): scale 26 f32 against the 16 GiB v5e-class default —
    the full-edge sort alone is ~20 GiB of arguments+outputs."""
    res = obs_devices.fit_check(26)
    assert not res.fits
    over = [s for s in res.stages
            if s.bytes is not None and s.bytes > res.effective_limit]
    assert any(s.stage == "build/sort" for s in over)
    assert "DOES NOT FIT" in obs_devices.render_fit(res)


def test_fit_check_explicit_limit_and_sharded_scaling():
    # A tiny explicit limit fails even a small geometry...
    res = obs_devices.fit_check(14, limit_bytes=1 << 20)
    assert not res.fits and res.limit_source == "explicit"
    # ...and vertex-sharding over more chips shrinks the per-chip
    # solve residency (tables + state shard; the z image does not).
    r1 = obs_devices.fit_check(20, ndev=1, device_build=False,
                               vertex_sharded=True)
    r8 = obs_devices.fit_check(20, ndev=8, device_build=False,
                               vertex_sharded=True)
    s1 = {s.stage: s for s in r1.stages}["solve/step"].bytes
    s8 = {s.stage: s for s in r8.stages}["solve/step"].bytes
    assert s8 < s1


def test_fit_check_refuses_int32_overflow_geometry():
    """The same capacity guard the real builder enforces surfaces as a
    preflight ERROR stage, not a crash: a striped sort key past int32
    is a verdict."""
    res = obs_devices.fit_check(n=1 << 28, num_edges=1 << 30,
                                dtype="float64", accum_dtype="float64",
                                wide_accum="pair")
    errs = [s for s in res.stages if s.source == "error"]
    assert not res.fits
    assert any("int32" in s.detail for s in errs)


def test_fit_slot_row_estimate_upper_bounds_real_build():
    """Soundness of the one modeled build quantity: the slot-row
    estimate must upper-bound what the real device build packs at the
    same geometry THROUGH THE PLANNED LAYOUT (gauge build.slot_rows) —
    fit_check models the plan_build layout, whose grouped lanes keep
    slots/edge in the 1.1-1.4 band SLOT_ROW_SLACK covers (group=1
    worst-case layouts are not what any planned build packs)."""
    from pagerank_tpu.ops import device_build as db

    for scale, ef in ((12, 8), (14, 16)):
        cfg = PageRankConfig(num_iters=1).validate()
        grp, stripe, _part = db.plan_build(cfg, 1 << scale,
                                           num_edges=ef << scale)
        src, dst = db.rmat_edges_device(scale, ef, seed=0)
        obs.get_registry().reset()
        db.build_ell_device(src, dst, n=1 << scale, group=grp,
                            stripe_size=stripe, with_weights=False)
        actual = obs.get_registry().snapshot()["gauges"][
            "build.slot_rows"]
        n_padded = 1 << scale
        sz = min(stripe, n_padded) if stripe else n_padded
        n_stripes = -(-n_padded // sz)
        est = obs_devices.estimate_slot_rows(ef << scale, n_padded,
                                             n_stripes)
        assert est >= actual, (scale, est, actual)


def test_obs_fit_cli_exit_codes(capsys):
    from pagerank_tpu.obs.__main__ import main as obs_main

    assert obs_main(["fit", "--scale", "14"]) == 0
    out = capsys.readouterr().out
    assert "FITS" in out and "solve/step" in out
    assert obs_main(["fit", "--scale", "26"]) == 1
    assert "DOES NOT FIT" in capsys.readouterr().out
    # --json emits a strict-JSON FitResult.
    assert obs_main(["fit", "--scale", "14", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["fits"] is True and doc["stages"]
    # Usage errors exit 2.
    assert obs_main(["fit", "--scale", "14", "--hbm-gb", "-1"]) == 2
    assert obs_main(["fit", "--scale", "14", "--headroom", "2"]) == 2
    capsys.readouterr()


def test_fit_device_kind_table_lookup():
    res = obs_devices.fit_check(14, device_kind="TPU v4")
    assert res.limit_bytes == 32 << 30
    assert "v4" in res.limit_source.lower() or "TPU v4" in res.limit_source
    assert obs_costs.hbm_capacity_bytes("TPU v5 lite") == 16 << 30
    assert obs_costs.hbm_capacity_bytes("unknown chip") is None


def test_explicit_device_kind_beats_live_limit(monkeypatch):
    """--device-kind exists to size for a chip that is NOT attached:
    an explicit kind must win over whatever the live backend reports
    (review finding: it used to be shadowed by bytes_limit)."""
    live = [mesh_lib.DeviceStats(id=0, platform="tpu", kind="TPU v5e",
                                 process_index=0, bytes_in_use=1,
                                 bytes_limit=16 << 30)]
    monkeypatch.setattr(mesh_lib, "device_stats", lambda d=None: live)
    limit, source = obs_devices.resolve_hbm_limit(
        device_kind="TPU v5p")
    assert limit == 95 << 30 and "v5p" in source.lower()
    # Without an explicit kind the live limit still wins.
    limit, source = obs_devices.resolve_hbm_limit()
    assert limit == 16 << 30 and source == "device bytes_limit"
    # An unknown explicit kind warns and falls through to the live
    # limit rather than silently defaulting.
    limit, _source = obs_devices.resolve_hbm_limit(
        device_kind="made-up chip")
    assert limit == 16 << 30


def test_fit_build_stages_gate_wide_meshes_too():
    """Review finding: the device build is single-chip regardless of
    the solve mesh — a scale-26 device build must be refused even at
    --ndev 8 (it used to silently skip the build stages and pass)."""
    res = obs_devices.fit_check(26, ndev=8, vertex_sharded=True)
    assert not res.fits
    assert any(s.stage == "build/sort" for s in res.stages)


def test_synthetic_spec_parser_is_shared_with_load_graph():
    """The preflight geometry parser and load_graph share ONE grammar:
    defaults agree with the generators' (rmat scale 20, edge factor
    16), and malformed specs are None (load_graph converts that to its
    clean error)."""
    from pagerank_tpu.cli import _parse_synthetic_geometry as parse

    assert parse("rmat:14") == ("rmat", 1 << 14, 16 << 14, 14)
    assert parse("rmat") == ("rmat", 1 << 20, 16 << 20, 20)
    assert parse("uniform:1000:5000") == ("uniform", 1000, 5000, None)
    assert parse("uniform:1000") == ("uniform", 1000, 16000, None)
    assert parse("banana:3") is None
    assert parse("uniform:abc") is None


def test_cli_preflight_blocks_doomed_run(tmp_path):
    """CLI --preflight: a geometry that cannot fit exits 3 BEFORE any
    graph work; a healthy one proceeds and the run report carries the
    devices section."""
    from pagerank_tpu.cli import main

    with pytest.raises(SystemExit) as ei:
        main(["--synthetic", "rmat:26", "--device-build",
              "--iters", "1", "--preflight", "--log-every", "0"])
    assert ei.value.code == 3
    report = str(tmp_path / "rr.json")
    rc = main(["--synthetic", "rmat:8", "--iters", "2", "--preflight",
               "--device-sample-every", "1", "--run-report", report,
               "--log-every", "0"])
    assert rc == 0
    doc = json.load(open(report))
    assert doc["devices"]["samples"] >= 2
    assert "device.0.bytes_in_use" in doc["metrics"]["gauges"]
    # The CLI tore the sampler back down on exit.
    assert obs_devices.get_sampler() is None


def test_bench_preflight_blocks(tmp_path):
    import bench
    from pagerank_tpu.exitcodes import ExitCode

    with pytest.raises(SystemExit) as ei:
        bench.main(["--scale", "26", "--preflight"])
    # Unified with the CLI's refusal code by the ISSUE-12 exit-code
    # taxonomy (bench exited 2 for this before).
    assert ei.value.code == int(ExitCode.PREFLIGHT_UNFIT)


def test_bench_multichip_preflight_models_clamped_mesh(monkeypatch):
    """Review finding: the multichip preflight must model the mesh the
    legs ACTUALLY run on (run_multichip clamps to visible devices) —
    an unclamped wider mesh shards the modeled residency thinner than
    reality and passes runs that then OOM."""
    import argparse

    import bench

    seen = {}
    real = obs_devices.fit_check

    def spy(*a, **k):
        seen.update(k)
        return real(*a, **k)

    monkeypatch.setattr(obs_devices, "fit_check", spy)
    args = argparse.Namespace(multichip=True, multichip_devices=64,
                              scale=10, edge_factor=16, dtype=None,
                              host_build=False)
    assert bench._preflight(args)
    assert seen["ndev"] == len(jax.devices())


def test_fit_check_plans_at_caller_layout_flags(monkeypatch):
    """Review finding: the preflight must gate the build the run will
    ACTUALLY execute — explicit stripe/lane-group/partition-span flags
    thread through to the shared planner (a default-layout gate could
    refuse a build that fits under the user's striping, or pass one
    that then OOMs)."""
    from pagerank_tpu.ops import device_build as db

    seen = {}
    real = db.plan_build

    def spy(cfg, n, **kw):
        seen.update(kw)
        return real(cfg, n, **kw)

    # fit_check resolves plan_build from the module at call time, so
    # patching the module attribute intercepts it.
    monkeypatch.setattr(db, "plan_build", spy)
    res = obs_devices.fit_check(14, stripe_size=512, lane_group=16,
                                partition_span=0)
    assert res.stages
    assert seen["stripe_size"] == 512 and seen["lane_group"] == 16
    # And an explicit partition span engages the partitioned geometry
    # (the planner returns the span as the pack stripe).
    obs_devices.fit_check(14, partition_span=512)
    assert seen["partition_span"] == 512


def test_exchange_program_reset_on_rebuild():
    """Review finding: a rebuild must drop the previous layout's
    exchange-only program — the jitted fn closes over the old
    mesh/state width, and attribution after an in-place rebuild must
    time the NEW build's exchange."""
    ndev = min(4, len(jax.devices()))
    if ndev < 2:
        pytest.skip("needs a multi-device mesh")
    cfg = PageRankConfig(num_iters=2, dtype="float32",
                         accum_dtype="float32", num_devices=ndev,
                         vertex_sharded=True)
    eng = JaxTpuEngine(cfg).build(_graph(n=512, e=4096, seed=1))
    att1 = obs_devices.attribute_exchange(eng, iters=2, warmup=1)
    assert att1 is not None and eng._exchange_fn is not None
    # Rebuild on a DIFFERENT graph size: the stale jit must be gone,
    # and attribution against the new build must work.
    eng.build(_graph(n=1024, e=8192, seed=2))
    assert eng._exchange_fn is None
    att2 = obs_devices.attribute_exchange(eng, iters=2, warmup=1)
    assert att2 is not None and att2["exchange_s"] > 0


def test_fit_unknown_memory_analysis_does_not_block(monkeypatch):
    """Review finding: a backend that compiles but reports no
    memory_analysis degrades build stages to source='unknown' — they
    are surfaced in the table but never force does-not-fit (telemetry
    degradation is not an OOM; only 'error' stages refuse)."""
    from pagerank_tpu.utils import jax_compat

    monkeypatch.setattr(jax_compat, "compiled_memory_analysis",
                        lambda compiled: None)
    res = obs_devices.fit_check(14)
    build = [s for s in res.stages if s.stage.startswith("build/")]
    assert build and all(s.source == "unknown" and s.bytes is None
                         for s in build)
    assert res.fits  # the analytic solve stage still gates — and fits
    rendered = obs_devices.render_fit(res)
    assert "?" in rendered and "ERROR" not in rendered


def test_solve_stage_models_striped_table_rows(monkeypatch):
    """Review finding: the solve-residency model must count the SAME
    striped table the build stages size — a hardcoded n_stripes=1
    under-modeled the stripe-padding rows (one per (stripe, dst
    block)), so a preflight near the HBM ceiling could pass a solve
    that then OOMs on the real striped tables."""
    calls = []
    real = obs_devices.estimate_slot_rows

    def spy(num_edges, n_padded, n_stripes):
        calls.append(n_stripes)
        return real(num_edges, n_padded, n_stripes)

    monkeypatch.setattr(obs_devices, "estimate_slot_rows", spy)
    obs_devices.fit_check(12, stripe_size=512, device_build=True)
    # n_padded=4096 at stripe 512 -> 8 stripes; BOTH the build scatter
    # sizing and the solve model must see them.
    assert calls and all(c == 8 for c in calls), calls

    # And the striped table is strictly bigger than a single-stripe
    # read of the same geometry (the padding rows are real bytes).
    cfg = PageRankConfig(num_iters=1, dtype="float32",
                         accum_dtype="float32").validate()
    striped = obs_devices._solve_stage_report(
        cfg, 1 << 12, 16 << 12, 1, False, stripe=512)
    flat = obs_devices._solve_stage_report(
        cfg, 1 << 12, 16 << 12, 1, False, stripe=0)
    assert striped.bytes > flat.bytes


def test_fit_check_models_vs_bounded_transients():
    """Review finding: --vs-bounded bounds per-chip step transients to
    O(stripe_span + N/ndev) — the preflight must model THAT mode, not
    refuse the geometry against the plain mode's full-width z image
    and merge accumulators (the flag exists precisely for runs the
    plain model busts)."""
    plain = obs_devices.fit_check(20, ndev=8, vertex_sharded=True,
                                  device_build=False)
    bounded = obs_devices.fit_check(20, ndev=8, vertex_sharded=True,
                                    vs_bounded=True, device_build=False)
    s_plain = next(s for s in plain.stages if s.stage == "solve/step")
    s_bound = next(s for s in bounded.stages if s.stage == "solve/step")
    assert s_bound.bytes < s_plain.bytes
    assert "vs-bounded" in s_bound.detail


def test_cli_preflight_threads_vs_bounded(monkeypatch):
    """The CLI gate models the run's OWN memory mode: --vs-bounded
    reaches fit_check (a plain-mode verdict for a bounded run renders
    the wrong answer in both directions)."""
    import argparse

    from pagerank_tpu import cli

    seen = {}
    real = obs_devices.fit_check

    def spy(*a, **k):
        seen.update(k)
        return real(*a, **k)

    monkeypatch.setattr(obs_devices, "fit_check", spy)
    args = argparse.Namespace(
        num_devices=2, vertex_sharded=True, vs_bounded=True,
        dtype="float32", accum_dtype=None, lane_group=None,
        partition_span=None,
    )
    cli._run_preflight(args, n=1 << 12, num_edges=16 << 12, scale=None,
                       device_build=False)
    assert seen["vs_bounded"] is True and seen["vertex_sharded"] is True


def test_track_pid_base_clears_linux_pid_space():
    """Review finding: per-device counter-track pids must never
    collide with the real process pid in the Chrome trace — the base
    sits above the kernel's maximum pid_max (2^22 on Linux)."""
    assert obs_devices.TRACK_PID_BASE > 1 << 22


def test_bench_multichip_preflight_gates_single_chip_leg(monkeypatch):
    """Review finding: run_multichip's FIRST leg is a single-chip
    solve (full-width tables/state on one chip, ~ndev x the sharded
    residency) — the preflight must gate THAT geometry too, not just
    the ndev-sharded legs, and must refuse before the sharded check
    when it busts."""
    import argparse

    import bench

    calls = []
    real = obs_devices.fit_check

    def spy(*a, **k):
        calls.append(k.get("ndev"))
        res = real(*a, **k)
        if k.get("ndev") == 1:
            res.fits = False
        return res

    monkeypatch.setattr(obs_devices, "fit_check", spy)
    args = argparse.Namespace(multichip=True, multichip_devices=8,
                              scale=10, edge_factor=16, dtype=None,
                              host_build=False)
    assert not bench._preflight(args)
    assert calls == [1]  # refused on the single-chip leg, sharded never ran


def test_sampler_resolves_callable_device_source():
    """Review finding: the sampler must be narrowable to the SOLVE
    MESH (a callable source, the watchdog idiom) so the watermark
    never attributes a foreign job's HBM peak to this run; a source
    that raises (pre-build boundary sample) degrades to the full
    sweep instead of failing the run."""
    s = obs_devices.DeviceSampler(every=1,
                                  devices=lambda: jax.devices()[:1])
    stats = s.sample()
    assert len(stats) == 1 and stats[0].id == jax.devices()[0].id

    def boom():
        raise RuntimeError("engine not built")

    degraded = obs_devices.DeviceSampler(every=1, devices=boom)
    assert len(degraded.sample()) == len(jax.devices())
