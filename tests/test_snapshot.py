"""Checkpoint/resume + failure recovery (SURVEY.md §5: fault injection =
kill-and-resume; resume must refuse mismatched graphs)."""

import numpy as np
import pytest

from pagerank_tpu import JaxTpuEngine, PageRankConfig, ReferenceCpuEngine, build_graph
from pagerank_tpu.utils.snapshot import Snapshotter, TextDumper, resume_engine


def test_text_dumper_reference_format(tmp_path):
    # Mirrors the reference's per-iteration saveAsTextFile layout:
    # <dir>/PageRank{i}/part-00000 with (key,rank) tuple lines.
    d = TextDumper(str(tmp_path / "dumps"), names=["a", "b"])
    p = d.dump(3, np.array([1.5, 0.25]))
    assert p.endswith("PageRank3/part-00000")
    lines = open(p).read().splitlines()
    assert lines == ["(a,1.5)", "(b,0.25)"]
    # integer keys when no name table
    d2 = TextDumper(str(tmp_path / "dumps2"))
    p2 = d2.dump(0, np.array([2.0]))
    assert open(p2).read() == "(0,2.0)\n"


def test_native_formatter_matches_python_repr_bytes():
    """The native bulk formatter (the L4 fast path) must be BYTE-
    identical to the Python per-line formatter — shortest-roundtrip
    digits AND CPython's presentation policy (fixed vs scientific cut,
    trailing .0, 2-digit exponents, inf/nan/-0.0 spellings) — across
    boundary values and raw random bit patterns."""
    import struct

    from pagerank_tpu.ingest import native

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(3)
    vals = [
        0.0, -0.0, 1.0, -1.0, 0.1, 1 / 3, 1e15, 1e16, 1e17, -1e16,
        9.999999999999999e15, 1e-4, 1e-5, -1e-5, 0.0001, 0.00001,
        1e100, 1e-100, 5e-324, 1.7976931348623157e308,
        float("inf"), float("-inf"), float("nan"),
        2.0, 0.25, 1.5, 123456.789, 9007199254740993.0,
    ]
    vals += list(rng.standard_normal(2000))
    vals += list(rng.standard_normal(1000) * 1e300)
    vals += list(rng.standard_normal(1000) * 1e-300)
    bits = rng.integers(0, 1 << 64, 4000, dtype=np.uint64)
    vals += [struct.unpack("<d", struct.pack("<Q", int(b)))[0] for b in bits]
    arr = np.array(vals, np.float64)
    got = native.format_rank_lines_native(arr)
    want = "".join(f"({i},{float(r)!r})\n" for i, r in enumerate(arr)).encode()
    assert got == want

    names = ["http://ex.com/a", "b", "日本語", "x" * 100]
    arr2 = np.array([1.5, 0.25, 1e-7, 3.0])
    enc = [s.encode() for s in names]
    offs = np.zeros(5, np.int64)
    np.cumsum([len(b) for b in enc], out=offs[1:])
    got2 = native.format_rank_lines_native(arr2, b"".join(enc), offs)
    want2 = "".join(
        f"({k},{float(r)!r})\n" for k, r in zip(names, arr2)
    ).encode()
    assert got2 == want2


def test_text_dumper_native_and_python_paths_agree(tmp_path, monkeypatch):
    """TextDumper writes the same part-file bytes whether or not the
    native formatter is available (f32 inputs widen to double first on
    both paths)."""
    from pagerank_tpu.ingest import native as native_mod

    if not native_mod.available():
        pytest.skip("native library unavailable")
    ranks = np.array([1.5, 0.3333333333333333, 1e-20, 7.0], np.float32)
    d1 = TextDumper(str(tmp_path / "fast"), names=["a", "b", "c", "d"])
    p1 = d1.dump(0, ranks)
    monkeypatch.setattr(
        "pagerank_tpu.ingest.native.format_rank_lines_native",
        lambda *a, **k: None,
    )
    d2 = TextDumper(str(tmp_path / "slow"), names=["a", "b", "c", "d"])
    p2 = d2.dump(0, ranks)
    assert open(p1, "rb").read() == open(p2, "rb").read()


def test_text_dumper_chunked_writes_match_unchunked(tmp_path, monkeypatch):
    """Forcing tiny write chunks (the bounded-RSS path) produces the
    same bytes as one chunk, integer and named keys alike."""
    from pagerank_tpu.utils.snapshot import TextDumper as TD

    rng = np.random.default_rng(9)
    ranks = rng.random(1000)
    names = [f"http://x/{i}" for i in range(1000)]
    d_ref = TD(str(tmp_path / "one"), names=names)
    p_ref = d_ref.dump(0, ranks)
    monkeypatch.setattr(TD, "CHUNK_ROWS", 37)
    d_c = TD(str(tmp_path / "chunked"), names=names)
    p_c = d_c.dump(0, ranks)
    assert open(p_c, "rb").read() == open(p_ref, "rb").read()
    di_ref = TD(str(tmp_path / "ione"))
    monkeypatch.setattr(TD, "CHUNK_ROWS", 1 << 20)
    pi_ref = di_ref.dump(0, ranks)
    monkeypatch.setattr(TD, "CHUNK_ROWS", 37)
    di_c = TD(str(tmp_path / "ichunked"))
    pi_c = di_c.dump(0, ranks)
    assert open(pi_c, "rb").read() == open(pi_ref, "rb").read()


def toy_graph(seed=0, n=50, e=300):
    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


CFG = PageRankConfig(num_iters=10, dtype="float64", accum_dtype="float64")


def test_save_load_roundtrip(tmp_path):
    g = toy_graph()
    s = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    r = np.arange(5, dtype=np.float64)
    s.save(3, r)
    ranks, meta = s.load(3)
    np.testing.assert_array_equal(ranks, r)
    assert meta["iteration"] == 3
    assert meta["fingerprint"] == g.fingerprint()
    assert s.latest() == 3
    s.save(7, r)
    assert s.latest() == 7


def test_kill_and_resume_matches_uninterrupted_run(tmp_path):
    """Fault injection: run 10 iters straight vs run 4, 'crash', resume
    from snapshot, finish — identical final ranks."""
    g = toy_graph()
    full = JaxTpuEngine(CFG).build(g).run()

    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    eng1 = JaxTpuEngine(CFG).build(g)
    eng1.run(
        num_iters=4,
        on_iteration=lambda i, info: snap.save(i + 1, eng1.ranks()),
    )
    del eng1  # "crash"

    eng2 = JaxTpuEngine(CFG).build(g)
    it = resume_engine(eng2, snap)
    assert it == 4
    r = eng2.run()
    np.testing.assert_allclose(r, full, rtol=0, atol=1e-13)


def test_resume_with_no_snapshot_is_noop(tmp_path):
    g = toy_graph()
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    eng = ReferenceCpuEngine(CFG).build(g)
    assert resume_engine(eng, snap) == 0
    assert eng.iteration == 0


def test_resume_refuses_wrong_graph(tmp_path):
    g1, g2 = toy_graph(0), toy_graph(1)
    s1 = Snapshotter(str(tmp_path), g1.fingerprint(), "reference")
    s1.save(5, np.ones(g1.n))
    eng = ReferenceCpuEngine(CFG).build(g2)
    s2 = Snapshotter(str(tmp_path), g2.fingerprint(), "reference")
    with pytest.raises(ValueError, match="fingerprint"):
        resume_engine(eng, s2)


def test_resume_refuses_wrong_semantics(tmp_path):
    g = toy_graph()
    s1 = Snapshotter(str(tmp_path), g.fingerprint(), "reference")
    s1.save(5, np.ones(g.n))
    eng = ReferenceCpuEngine(CFG.replace(semantics="textbook")).build(g)
    s2 = Snapshotter(str(tmp_path), g.fingerprint(), "textbook")
    with pytest.raises(ValueError, match="semantics"):
        resume_engine(eng, s2)


def test_async_rank_writer_matches_sync(tmp_path):
    """CLI: async offload (default) writes byte-identical snapshots and
    text dumps to --sync-io."""
    import filecmp

    from pagerank_tpu.cli import main

    edges = tmp_path / "e.txt"
    rng = np.random.default_rng(2)
    edges.write_text(
        "".join(f"{s} {d}\n" for s, d in
                zip(rng.integers(0, 60, 400), rng.integers(0, 60, 400)))
    )
    outs = {}
    for mode, extra in (("async", []), ("sync", ["--sync-io"])):
        sd = tmp_path / f"snap_{mode}"
        td = tmp_path / f"dump_{mode}"
        assert main(["--input", str(edges), "--iters", "6",
                     "--snapshot-dir", str(sd), "--dump-text-dir", str(td),
                     "--log-every", "0", *extra]) == 0
        outs[mode] = (sd, td)
    sa, ta = outs["async"]; ss, ts = outs["sync"]
    snaps = sorted(p.name for p in sa.iterdir())
    assert snaps == sorted(p.name for p in ss.iterdir()) and len(snaps) == 6
    for name in snaps:
        za = np.load(sa / name); zs = np.load(ss / name)
        np.testing.assert_array_equal(za["ranks"], zs["ranks"])
    for i in range(6):
        fa = ta / f"PageRank{i}" / "part-00000"
        fs = ts / f"PageRank{i}" / "part-00000"
        assert filecmp.cmp(fa, fs, shallow=False), i


def test_async_rank_writer_error_propagates():
    from pagerank_tpu.utils.snapshot import AsyncRankWriter

    def bad_sink(i, ranks):
        raise IOError("disk full")

    w = AsyncRankWriter(lambda p: np.asarray(p), [bad_sink], max_pending=2)
    w.submit(0, np.ones(4))
    with pytest.raises(RuntimeError, match="disk full"):
        w.close()


def test_async_rank_writer_backpressure_and_order(tmp_path):
    from pagerank_tpu.utils.snapshot import AsyncRankWriter

    seen = []
    w = AsyncRankWriter(lambda p: p, [lambda i, r: seen.append((i, float(r[0])))],
                        max_pending=1)
    for i in range(20):
        w.submit(i, np.full(2, i, dtype=np.float64))
    w.close()
    assert seen == [(i, float(i)) for i in range(20)]


def test_cli_async_writer_failure_fails_the_run(tmp_path, monkeypatch):
    """A write failure surfacing only at close() must fail the CLI, not
    be swallowed by the cleanup path."""
    from pagerank_tpu import cli as cli_mod
    from pagerank_tpu.utils import snapshot as snap_mod

    edges = tmp_path / "e.txt"
    edges.write_text("0 1\n1 2\n2 0\n")

    real_save = snap_mod.Snapshotter.save

    def failing_save(self, iteration, ranks):
        if iteration >= 3:
            raise IOError("disk full")
        return real_save(self, iteration, ranks)

    monkeypatch.setattr(snap_mod.Snapshotter, "save", failing_save)
    with pytest.raises(RuntimeError, match="disk full"):
        cli_mod.main(["--input", str(edges), "--iters", "5",
                      "--snapshot-dir", str(tmp_path / "s"),
                      "--log-every", "0"])


def test_text_dumper_writes_success_marker(tmp_path):
    d = TextDumper(str(tmp_path))
    d.dump(0, np.array([1.0, 2.0]))
    assert (tmp_path / "PageRank0" / "_SUCCESS").exists()


def test_sharded_save_gathers_to_host_before_checksumming(tmp_path):
    """ISSUE-7 hardening: saving a SHARDED device array (the
    vertex-sharded engine's rank vector lives split across the mesh)
    must gather to ONE host buffer before checksumming — the digest
    has to cover the exact bytes written, not a per-shard view. The
    saved file then verifies and round-trips bit-identically."""
    import jax

    rng = np.random.default_rng(4)
    n, e = 512, 4096
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    ndev = min(4, len(jax.devices()))
    cfg = PageRankConfig(num_iters=3, dtype="float32",
                         accum_dtype="float32", num_devices=ndev,
                         vertex_sharded=True)
    eng = JaxTpuEngine(cfg).build(g)
    eng.run()
    sharded = eng._r  # the live sharded device buffer
    assert not isinstance(sharded, np.ndarray)
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference",
                       mesh_meta=eng.snapshot_meta())
    snap.save(3, sharded)
    loaded, meta = snap.load(3)  # verify=True: checksum must hold
    np.testing.assert_array_equal(
        loaded, np.asarray(jax.device_get(sharded))
    )
    assert meta["mesh"]["vertex_sharded"] is True
    assert meta["mesh"]["num_devices"] == ndev


def test_sharded_engine_snapshot_resumes_single_device_f32(tmp_path):
    """Regression for the ISSUE-7 satellite: a snapshot taken from a
    SHARDED (vertex-sharded, N-device) engine must load onto a
    single-device engine bit-identically at f32 grade."""
    import jax

    ndev = min(8, len(jax.devices()))
    if ndev < 2:
        pytest.skip("needs a multi-device fake mesh")
    rng = np.random.default_rng(6)
    n, e = 512, 4096
    g = build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)
    cfg = PageRankConfig(num_iters=4, dtype="float32",
                         accum_dtype="float32", num_devices=ndev,
                         vertex_sharded=True)
    eng = JaxTpuEngine(cfg).build(g)
    snap = Snapshotter(str(tmp_path), g.fingerprint(), "reference",
                       mesh_meta=eng.snapshot_meta())
    eng.run(on_iteration=lambda i, info: snap.save(i + 1, eng.ranks()))
    r_sharded = eng.ranks()

    single = PageRankConfig(num_iters=4, dtype="float32",
                            accum_dtype="float32", num_devices=1)
    e1 = JaxTpuEngine(single).build(g)
    assert resume_engine(e1, snap) == 4
    np.testing.assert_array_equal(e1.ranks(), r_sharded)
