"""Blocked-ELL packing + kernel tests (ops/ell.py, ops/spmv.py:ell_contrib)."""

import numpy as np
import pytest

from pagerank_tpu import JaxTpuEngine, PageRankConfig, ReferenceCpuEngine, build_graph
from pagerank_tpu.graph import to_csr_transpose
from pagerank_tpu.ops import ell as ell_lib


def random_graph(seed=0, n=300, e=2500):
    rng = np.random.default_rng(seed)
    return build_graph(rng.integers(0, n, e), rng.integers(0, n, e), n=n)


def test_pack_roundtrip_spmv_matches_csr():
    g = random_graph()
    pack = ell_lib.ell_pack(g)
    rng = np.random.default_rng(1)
    z = rng.random(g.n)
    # relabeled input/output
    y_rel = ell_lib.ell_spmv_reference(pack, z[pack.perm])
    y = np.empty(g.n)
    y[pack.perm] = y_rel
    expected = to_csr_transpose(g) @ z
    np.testing.assert_allclose(y, expected, rtol=1e-12)


def test_pack_invariants():
    g = random_graph(seed=3, n=500, e=4000)
    pack = ell_lib.ell_pack(g)
    assert pack.n == g.n
    assert pack.n_padded % 128 == 0
    # row_block ascending
    assert np.all(np.diff(pack.row_block) >= 0)
    # slot weights: real slots hold 1/out_degree, padding zero; total
    # count of nonzero slots == edge count
    assert (pack.weight > 0).sum() == g.num_edges
    # in-degree-descending relabel => block depths are non-increasing-ish:
    # first block's depth is the global max in-degree
    if pack.num_rows:
        first_rows = int((pack.row_block == 0).sum())
        assert first_rows == int(g.in_degree.max())
    # perm/inv_perm inverse of each other
    np.testing.assert_array_equal(pack.perm[pack.inv_perm], np.arange(g.n))


def test_pack_padding_reasonable_on_powerlaw():
    from pagerank_tpu.utils.synth import rmat_edges

    src, dst = rmat_edges(16, 16, seed=0)
    g = build_graph(src, dst, n=1 << 16)
    pack = ell_lib.ell_pack(g)
    # degree-sorted relabeling keeps ELL padding modest on power-law
    # graphs (measured: 2.2x at scale 14 shrinking to 1.27x at scale 20;
    # the ratio falls as blocks get denser).
    assert pack.padding_ratio < 2.0, pack.padding_ratio


def test_empty_graph_pack():
    g = build_graph(np.array([], np.int64), np.array([], np.int64), n=10)
    pack = ell_lib.ell_pack(g)
    assert pack.num_rows == 0
    y = ell_lib.ell_spmv_reference(pack, np.ones(10))
    np.testing.assert_array_equal(y, 0)


@pytest.mark.parametrize("ndev", [1, 8])
def test_ell_engine_matches_oracle(ndev):
    g = random_graph(seed=7)
    cfg = PageRankConfig(
        num_iters=12, dtype="float64", accum_dtype="float64",
        kernel="ell", num_devices=ndev,
    )
    r_ell = JaxTpuEngine(cfg).build(g).run()
    r_cpu = ReferenceCpuEngine(cfg).build(g).run()
    np.testing.assert_allclose(r_ell, r_cpu, rtol=0, atol=1e-12)


def test_ell_equals_coo_engine():
    g = random_graph(seed=9, n=700, e=6000)
    base = PageRankConfig(num_iters=10, dtype="float64", accum_dtype="float64")
    r_ell = JaxTpuEngine(base.replace(kernel="ell")).build(g).run()
    r_coo = JaxTpuEngine(base.replace(kernel="coo")).build(g).run()
    np.testing.assert_allclose(r_ell, r_coo, rtol=0, atol=1e-12)


def test_ell_set_ranks_roundtrip():
    g = random_graph(seed=11)
    cfg = PageRankConfig(num_iters=3, kernel="ell", dtype="float64",
                         accum_dtype="float64")
    eng = JaxTpuEngine(cfg).build(g)
    rng = np.random.default_rng(0)
    r = rng.random(g.n)
    eng.set_ranks(r, iteration=5)
    np.testing.assert_allclose(eng.ranks(), r, rtol=0, atol=0)
    assert eng.iteration == 5


def test_ell_non_multiple_of_128_vertices():
    g = random_graph(seed=13, n=200, e=900)  # 200 -> padded 256
    cfg = PageRankConfig(num_iters=8, kernel="ell", dtype="float64",
                         accum_dtype="float64")
    r = JaxTpuEngine(cfg).build(g).run()
    r_cpu = ReferenceCpuEngine(cfg).build(g).run()
    np.testing.assert_allclose(r, r_cpu, rtol=0, atol=1e-12)


@pytest.mark.parametrize("group", [2, 8, 64, 128])
def test_grouped_pack_spmv_matches_csr(group):
    # Grouped-lane layout (slot serves any of `group` adjacent dsts via a
    # packed sub-lane) must compute the exact same SpMV.
    g = random_graph(seed=9, n=700, e=6000)
    pack = ell_lib.ell_pack(g, group=group)
    rng = np.random.default_rng(2)
    z = rng.random(g.n)
    y_rel = ell_lib.ell_spmv_reference(pack, z[pack.perm])
    y = np.empty(g.n)
    y[pack.perm] = y_rel
    np.testing.assert_allclose(y, to_csr_transpose(g) @ z, rtol=1e-12)
    # fewer or equal rows than the ungrouped pack
    assert pack.num_rows <= ell_lib.ell_pack(g).num_rows


def test_grouped_pack_shrinks_powerlaw_padding():
    from pagerank_tpu.utils.synth import rmat_edges

    s, d = rmat_edges(13, 12, seed=5)
    g = build_graph(s, d, n=1 << 13)
    p1 = ell_lib.ell_pack(g, group=1)
    p8 = ell_lib.ell_pack(g, group=8)
    assert p8.padding_ratio < p1.padding_ratio


@pytest.mark.parametrize("ndev", [1, 4])
def test_grouped_engine_matches_oracle(ndev):
    g = random_graph(seed=11, n=900, e=9000)
    cfg = PageRankConfig(
        num_iters=12, dtype="float64", accum_dtype="float64",
        lane_group=8, num_devices=ndev,
    )
    r = JaxTpuEngine(cfg).build(g).run_fast()
    r_ref = ReferenceCpuEngine(cfg).build(g).run()
    np.testing.assert_allclose(r, r_ref, rtol=0, atol=1e-12)


def test_grouped_pair_accum_matches_oracle():
    g = random_graph(seed=13, n=800, e=7000)
    cfg = PageRankConfig(
        num_iters=15, dtype="float32", accum_dtype="float64",
        wide_accum="pair", lane_group=8,
    )
    r = JaxTpuEngine(cfg).build(g).run_fast()
    cfg64 = cfg.replace(dtype="float64", wide_accum="auto", lane_group=1)
    r_ref = ReferenceCpuEngine(cfg64).build(g).run()
    np.testing.assert_allclose(r, r_ref, rtol=0, atol=1e-6)


def test_grouped_striped_engine_matches_oracle():
    class SmallStripe(JaxTpuEngine):
        def _stripe_max(self):
            return 256  # force several stripes

    g = random_graph(seed=15, n=1000, e=8000)
    cfg = PageRankConfig(
        num_iters=10, dtype="float64", accum_dtype="float64", lane_group=8,
    )
    r = SmallStripe(cfg).build(g).run_fast()
    r_ref = ReferenceCpuEngine(cfg).build(g).run()
    np.testing.assert_allclose(r, r_ref, rtol=0, atol=1e-12)


def test_autotune_chunk_times_candidates(monkeypatch):
    # Force the timing branch (normally TPU-only + big-table-only) on
    # CPU with a tiny graph: it must run the candidate ops and return
    # one of the candidates.
    import jax

    g = random_graph(seed=17, n=9000, e=150000)
    cfg = PageRankConfig(num_iters=2, lane_group=8)
    eng = JaxTpuEngine(cfg).build(g)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    rows = int(eng._src[0].shape[0])
    assert rows >= 512  # candidates must survive the rows filter
    P = int(np.asarray(eng._row_block[0]).max()) + 1
    chosen = eng._autotune_chunk(
        [256, 512], [rows], 1 << 23, 4, 8, 8, False, "float32", [P], 1
    )
    assert chosen in (256, 512)


def test_pallas_probe_failure_falls_back_to_ell(monkeypatch):
    # If Mosaic rejects every pallas gather strategy, the engine reruns
    # the pallas-built arrays (GLOBAL block ids) through the non-slab
    # ell path — results must still match the oracle.
    from pagerank_tpu.ops import pallas_spmv

    def boom(*a, **k):
        raise RuntimeError("mosaic says no")

    monkeypatch.setattr(pallas_spmv, "ell_contrib_pallas", boom)
    g = random_graph(seed=19, n=700, e=6000)
    cfg = PageRankConfig(num_iters=10, kernel="pallas")
    eng = JaxTpuEngine(cfg).build(g)
    assert eng._kernel == "ell"
    r = eng.run_fast()
    cfg64 = PageRankConfig(num_iters=10, dtype="float64",
                           accum_dtype="float64")
    r_ref = ReferenceCpuEngine(cfg64).build(g).run()
    np.testing.assert_allclose(r, r_ref, rtol=0, atol=1e-4)


def test_deal_block_order_properties():
    """deal_block_order (the vs_bounded dst deal): a valid block
    permutation with filled slots contiguous from 0, the partial block
    globally last, and near-equal per-device round-robin shares."""
    for n, ndev in [(1000, 8), (128 * 7, 4), (128 * 16, 8), (130, 8),
                    (100, 3), (128, 1)]:
        n_padded = -(-n // 128) * 128
        nb_fill = n_padded // 128
        new_of_old = ell_lib.deal_block_order(n, n_padded, ndev)
        assert sorted(new_of_old) == sorted(set(new_of_old))  # injective
        nbd = -(-nb_fill // ndev)
        assert new_of_old.max() < nbd * ndev
        # filled slots pack 0..nb_fill-1 (holes all trailing)
        assert set(new_of_old) == set(range(nb_fill))
        if n % 128:
            assert new_of_old[-1] == nb_fill - 1  # partial block last
        # round-robin: early full blocks spread one per device
        if nb_fill >= ndev:
            first_round = new_of_old[:ndev] // nbd
            assert sorted(first_round) == list(range(ndev))


def test_pack_with_deal_matches_undealt_spmv():
    """A dealt pack computes the same SpMV (in original id space) as
    the plain pack — only the relabel moves."""
    g = random_graph(seed=11, n=700, e=6000)
    rng = np.random.default_rng(2)
    z = rng.random(g.n)
    expected = to_csr_transpose(g) @ z

    for deal in (2, 8):
        pack = ell_lib.ell_pack(g, block_deal=deal)
        assert sorted(pack.perm) == list(range(g.n))  # still a permutation
        y_rel = ell_lib.ell_spmv_reference(pack, z[pack.perm])
        y = np.empty(g.n)
        y[pack.perm] = y_rel
        np.testing.assert_allclose(y, expected, rtol=1e-12)
        # dealing whole blocks preserves slot count (ELL padding)
        plain = ell_lib.ell_pack(g)
        assert pack.num_rows == plain.num_rows


def test_deal_balances_row_load():
    """On a power-law graph the dealt (LPT-weighted) block ranges carry
    near-equal row counts, where contiguous ranges are dominated by
    device 0 (the in-degree-descending relabel piles every hot block
    there). The residual imbalance is the single hottest block, which
    no assignment can split."""
    from pagerank_tpu.utils.synth import rmat_edges

    src, dst = rmat_edges(14, edge_factor=16, seed=5)
    g = build_graph(src, dst, n=1 << 14)
    ndev = 8
    pack = ell_lib.ell_pack(g, block_deal=ndev, group=16)
    nb = pack.n_padded // 128
    nbd = -(-nb // ndev)
    rows_per_dev = np.bincount(
        np.minimum(pack.row_block // nbd, ndev - 1), minlength=ndev
    )
    plain = ell_lib.ell_pack(g, group=16)
    plain_rows = np.bincount(
        np.minimum(plain.row_block // nbd, ndev - 1), minlength=ndev
    )
    depths = np.bincount(plain.row_block, minlength=nb)
    # LPT bound: max load <= mean + the hottest block
    assert rows_per_dev.max() <= rows_per_dev.mean() + depths.max()
    assert rows_per_dev.max() < plain_rows.max()
    assert plain_rows.max() > 2 * plain_rows.mean()
