"""In-process S3 stub server for tests (zero-egress environment).

Speaks just enough of the S3 REST dialect to exercise
pagerank_tpu.utils.s3 end-to-end: object GET/PUT/HEAD/DELETE,
server-side COPY (x-amz-copy-source), and ListObjectsV2 with
prefix/delimiter/max-keys/continuation-token pagination. Requests'
Authorization headers are recorded so tests can assert SigV4 signing
engaged (cryptographic verification of the signature itself is pinned
separately against the published AWS test vector in test_s3.py).
"""

from __future__ import annotations

import hashlib
import threading
import urllib.parse
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from xml.sax.saxutils import escape


class S3Stub:
    def __init__(self):
        self.objects = {}  # "/bucket/key" -> bytes
        self.etags = {}  # "/bucket/key" -> quoted ETag (md5 / multipart)
        self.lock = threading.RLock()
        self.auth_headers = []  # recorded Authorization values (or None)
        self.max_page = 1000  # shrink in tests to force pagination
        self.uploads = {}  # upload_id -> {"path": str, "parts": {num: bytes}}
        self.range_requests = []  # recorded Range header values
        self.completed_multiparts = []  # paths assembled via multipart
        self.fail_part = None  # part number to reject (fault injection)
        # HTTP-level fault hook (pagerank_tpu.testing.faults.
        # HttpFaultInjector): callable(method, path) -> None or an
        # action tuple — ("status", code[, code_str]) answer an error,
        # ("reset",) drop the connection without a response (client
        # sees RemoteDisconnected), ("truncate", nbytes) send a GET
        # body short of its Content-Length (client sees
        # IncompleteRead), ("commit_then_status", code) apply a
        # multipart COMPLETE server-side but answer an error — the
        # committed-but-response-lost case a non-idempotent complete
        # must recover from.
        self.fault_hook = None
        self._next_upload = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _fault(self, method):
                """Consult the fault hook; returns True when the fault
                fully handled (or dropped) the response, or the action
                tuple for handler-specific kinds."""
                if outer.fault_hook is None:
                    return None
                act = outer.fault_hook(method, self.path)
                if not act:
                    return None
                kind = act[0]
                if kind == "status":
                    code_str = act[2] if len(act) > 2 else "InternalError"
                    # consume the request body first: an unread body +
                    # error response can surface as a broken pipe on
                    # the client instead of the intended status
                    length = int(self.headers.get("Content-Length", 0))
                    if length:
                        self.rfile.read(length)
                    self._send(
                        act[1],
                        f"<Error><Code>{code_str}</Code></Error>".encode(),
                    )
                    return True
                if kind == "reset":
                    # No response at all + connection close: the client
                    # observes RemoteDisconnected (a ConnectionError).
                    self.close_connection = True
                    return True
                return act  # handler-specific ("truncate", "commit_then_status")

            def _path_query(self):
                u = urllib.parse.urlsplit(self.path)
                return urllib.parse.unquote(u.path), urllib.parse.parse_qs(
                    u.query, keep_blank_values=True
                )

            def _record(self):
                outer.auth_headers.append(self.headers.get("Authorization"))

            def _send(self, status, body=b"", ctype="application/xml",
                      head_len=None, etag=None):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                if etag:
                    self.send_header("ETag", etag)
                self.send_header(
                    "Content-Length",
                    str(head_len if head_len is not None else len(body)),
                )
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_PUT(self):
                self._record()
                if self._fault("PUT") is True:
                    return
                path, q = self._path_query()
                src = self.headers.get("x-amz-copy-source")
                if src:
                    src = urllib.parse.unquote(src)
                    with outer.lock:
                        if src not in outer.objects:
                            self._send(404, b"<Error><Code>NoSuchKey</Code></Error>")
                            return
                        sdata = outer.objects[src]
                        if "uploadId" in q:  # UploadPartCopy
                            rng = self.headers.get("x-amz-copy-source-range")
                            if rng:  # "bytes=lo-hi", inclusive
                                lo, hi = rng.split("=", 1)[1].split("-")
                                sdata = sdata[int(lo):int(hi) + 1]
                            up = outer.uploads.get(q["uploadId"][0])
                            if up is None or up["path"] != path:
                                self._send(
                                    404,
                                    b"<Error><Code>NoSuchUpload</Code></Error>",
                                )
                                return
                            num = int(q["partNumber"][0])
                            up["parts"][num] = sdata
                            etag = f'"{hashlib.md5(sdata).hexdigest()}"'
                            self._send(
                                200,
                                (f"<?xml version='1.0'?><CopyPartResult>"
                                 f"<ETag>{etag}</ETag>"
                                 f"</CopyPartResult>").encode(),
                            )
                            return
                        outer.objects[path] = sdata
                        outer.etags[path] = (
                            f'"{hashlib.md5(sdata).hexdigest()}"'
                        )
                    self._send(200, b"<CopyObjectResult/>")
                    return
                length = int(self.headers.get("Content-Length", 0))
                data = self.rfile.read(length) if length else b""
                if "uploadId" in q:  # UploadPart
                    num = int(q["partNumber"][0])
                    uid = q["uploadId"][0]
                    with outer.lock:
                        up = outer.uploads.get(uid)
                        if up is None or up["path"] != path:
                            self._send(404, b"<Error><Code>NoSuchUpload</Code></Error>")
                            return
                        if num == outer.fail_part:
                            self._send(500, b"<Error><Code>InternalError</Code></Error>")
                            return
                        up["parts"][num] = data
                        etag = f'"{hashlib.md5(data).hexdigest()}"'
                    self.send_response(200)
                    self.send_header("ETag", etag)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                with outer.lock:
                    outer.objects[path] = data
                    outer.etags[path] = f'"{hashlib.md5(data).hexdigest()}"'
                self._send(200)

            def do_POST(self):
                self._record()
                act = self._fault("POST")
                if act is True:
                    return
                commit_then_status = (
                    act[1] if act and act[0] == "commit_then_status" else None
                )
                path, q = self._path_query()
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                if "uploads" in q:  # InitiateMultipartUpload
                    with outer.lock:
                        outer._next_upload += 1
                        uid = f"upload-{outer._next_upload}"
                        outer.uploads[uid] = {"path": path, "parts": {}}
                    self._send(
                        200,
                        (f"<?xml version='1.0'?><InitiateMultipartUploadResult>"
                         f"<UploadId>{uid}</UploadId>"
                         f"</InitiateMultipartUploadResult>").encode(),
                    )
                    return
                if "uploadId" in q:  # CompleteMultipartUpload
                    uid = q["uploadId"][0]
                    with outer.lock:
                        up = outer.uploads.pop(uid, None)
                        if up is None or up["path"] != path:
                            self._send(404, b"<Error><Code>NoSuchUpload</Code></Error>")
                            return
                        # Validate the client's part list against what
                        # was uploaded (number order + ETag match).
                        want = []
                        for part in ET.fromstring(body):
                            fields = {c.tag.rsplit("}", 1)[-1]: c.text for c in part}
                            want.append(
                                (int(fields["PartNumber"]), fields["ETag"])
                            )
                        have = up["parts"]
                        ok = (
                            [n for n, _ in want] == sorted(have)
                            and all(
                                t == f'"{hashlib.md5(have[n]).hexdigest()}"'
                                for n, t in want
                            )
                        )
                        if not ok:
                            self._send(400, b"<Error><Code>InvalidPart</Code></Error>")
                            return
                        outer.objects[path] = b"".join(
                            have[n] for n, _ in want
                        )
                        # the real S3 multipart ETag form:
                        # md5(concat(binary part md5s))-<nparts>
                        bins = b"".join(
                            hashlib.md5(have[n]).digest() for n, _ in want
                        )
                        outer.etags[path] = (
                            f'"{hashlib.md5(bins).hexdigest()}-{len(want)}"'
                        )
                        outer.completed_multiparts.append(path)
                    if commit_then_status is not None:
                        # committed server-side, response "lost": the
                        # client must recover via ListParts + HEAD
                        self._send(
                            commit_then_status,
                            b"<Error><Code>InternalError</Code></Error>",
                        )
                        return
                    self._send(
                        200,
                        b"<?xml version='1.0'?><CompleteMultipartUploadResult>"
                        b"</CompleteMultipartUploadResult>",
                    )
                    return
                self._send(400, b"<Error><Code>BadRequest</Code></Error>")

            def do_GET(self):
                self._record()
                act = self._fault("GET")
                if act is True:
                    return
                truncate_at = act[1] if act and act[0] == "truncate" else None
                path, q = self._path_query()
                if q.get("list-type") == ["2"]:
                    self._do_list(path.strip("/"), q)
                    return
                if "uploadId" in q:  # ListParts
                    with outer.lock:
                        up = outer.uploads.get(q["uploadId"][0])
                        if up is None or up["path"] != path:
                            self._send(
                                404,
                                b"<Error><Code>NoSuchUpload</Code></Error>",
                            )
                            return
                        parts = "".join(
                            f"<Part><PartNumber>{n}</PartNumber>"
                            f'<ETag>"{hashlib.md5(d).hexdigest()}"</ETag>'
                            f"</Part>"
                            for n, d in sorted(up["parts"].items())
                        )
                    self._send(
                        200,
                        (f"<?xml version='1.0'?><ListPartsResult>{parts}"
                         f"</ListPartsResult>").encode(),
                    )
                    return
                with outer.lock:
                    data = outer.objects.get(path)
                if data is None:
                    self._send(404, b"<Error><Code>NoSuchKey</Code></Error>")
                    return
                if truncate_at is not None:
                    # Full Content-Length, short body, dropped
                    # connection: the client's read raises
                    # IncompleteRead — a mid-body connection reset.
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data[:truncate_at])
                    self.close_connection = True
                    return
                rng = self.headers.get("Range")
                if rng and rng.startswith("bytes="):
                    outer.range_requests.append(rng)
                    lo_s, _, hi_s = rng[6:].partition("-")
                    lo = int(lo_s)
                    if lo >= len(data):  # real S3: 416 InvalidRange
                        self._send(
                            416, b"<Error><Code>InvalidRange</Code></Error>")
                        return
                    hi = min(int(hi_s) if hi_s else len(data) - 1,
                             len(data) - 1)
                    body = data[lo:hi + 1]
                    self.send_response(206)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header(
                        "Content-Range", f"bytes {lo}-{hi}/{len(data)}")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self._send(200, data, ctype="application/octet-stream")

            def do_HEAD(self):
                self._record()
                if self._fault("HEAD") is True:
                    return
                path, _ = self._path_query()
                with outer.lock:
                    data = outer.objects.get(path)
                if data is None:
                    self._send(404, head_len=0)
                else:
                    self._send(200, ctype="application/octet-stream",
                               head_len=len(data),
                               etag=outer.etags.get(path))

            def do_DELETE(self):
                self._record()
                if self._fault("DELETE") is True:
                    return
                path, q = self._path_query()
                with outer.lock:
                    if "uploadId" in q:  # AbortMultipartUpload
                        outer.uploads.pop(q["uploadId"][0], None)
                    else:
                        outer.objects.pop(path, None)
                        outer.etags.pop(path, None)
                self._send(204)

            def _do_list(self, bucket, q):
                prefix = q.get("prefix", [""])[0]
                delim = q.get("delimiter", [""])[0]
                max_keys = min(int(q.get("max-keys", ["1000"])[0]),
                               outer.max_page)
                token = q.get("continuation-token", [""])[0]
                base = f"/{bucket}/"
                with outer.lock:
                    keys = sorted(
                        k[len(base):] for k in outer.objects
                        if k.startswith(base + prefix)
                    )
                # Collapse at the delimiter into CommonPrefixes.
                entries = []  # (sort_key, is_prefix)
                seen = set()
                for k in keys:
                    if delim:
                        rest = k[len(prefix):]
                        if delim in rest:
                            cp = prefix + rest.split(delim, 1)[0] + delim
                            if cp not in seen:
                                seen.add(cp)
                                entries.append((cp, True))
                            continue
                    entries.append((k, False))
                entries.sort()
                start = 0
                if token:
                    start = next(
                        (i for i, (k, _) in enumerate(entries) if k > token),
                        len(entries),
                    )
                page = entries[start:start + max_keys]
                truncated = start + max_keys < len(entries)
                parts = ["<?xml version='1.0'?><ListBucketResult>"]
                parts.append(f"<IsTruncated>{str(truncated).lower()}</IsTruncated>")
                for k, is_prefix in page:
                    if is_prefix:
                        parts.append(
                            f"<CommonPrefixes><Prefix>{escape(k)}</Prefix>"
                            f"</CommonPrefixes>"
                        )
                    else:
                        parts.append(f"<Contents><Key>{escape(k)}</Key></Contents>")
                if truncated and page:
                    parts.append(
                        f"<NextContinuationToken>{escape(page[-1][0])}"
                        f"</NextContinuationToken>"
                    )
                parts.append("</ListBucketResult>")
                self._send(200, "".join(parts).encode())

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )

    @property
    def endpoint(self) -> str:
        host, port = self.server.server_address[:2]
        return f"http://{host}:{port}"

    def __enter__(self):
        self.thread.start()
        return self

    def __exit__(self, *exc):
        self.server.shutdown()
        self.server.server_close()
        return False
